"""KV-cache decode: _contrib_CachedAttention + get_decode_symbol +
Generator.

The load-bearing check is teacher-forcing consistency: feeding a
sequence through the incremental decode path (prefill + one token at a
time) must reproduce the training symbol's per-position softmax.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.executor import _graph_eval_fn
from mxnet_tpu.generation import Generator
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.models import transformer
from mxnet_tpu.ops.attention import cached_attention, _attn_reference
from mxnet_tpu.parallel import make_train_step

V, L, H, DIM, T, B = 50, 2, 2, 32, 12, 2


def _trained_params(seed=0):
    sym = transformer.get_symbol(V, T, num_layers=L, num_heads=H,
                                 dim=DIM)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(seed)      # distinct seeds -> genuinely distinct
    state = step.init_state(Xavier(),
                            {"data": (B, T),
                             "softmax_label": (B, T)})
    return sym, state[0]


class TestCachedAttentionOp:
    def test_matches_reference_incremental(self):
        """Appending one token at a time over a causal sequence equals
        dense causal attention."""
        rng = np.random.RandomState(0)
        Tmax, hd = 8, 16
        q = jnp.asarray(rng.randn(1, 2, Tmax, hd), jnp.float32)
        k = jnp.asarray(rng.randn(1, 2, Tmax, hd), jnp.float32)
        v = jnp.asarray(rng.randn(1, 2, Tmax, hd), jnp.float32)
        kc = jnp.zeros((1, 2, Tmax, hd), jnp.float32)
        vc = jnp.zeros_like(kc)
        outs = []
        for t in range(Tmax):
            o, kc, vc = cached_attention(
                q[:, :, t:t + 1], k[:, :, t:t + 1], v[:, :, t:t + 1],
                kc, vc, jnp.full((1,), t))
            outs.append(o)
        inc = jnp.concatenate(outs, axis=2).reshape(2, Tmax, hd)
        ref = _attn_reference(q.reshape(2, Tmax, hd),
                              k.reshape(2, Tmax, hd),
                              v.reshape(2, Tmax, hd),
                              hd ** -0.5, True)
        np.testing.assert_allclose(np.asarray(inc), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6)

    def test_prefill_then_steps(self):
        """A multi-token prefill chunk equals the same tokens appended
        one by one."""
        rng = np.random.RandomState(1)
        Tmax, hd, P = 8, 8, 5
        mk = lambda: jnp.asarray(rng.randn(1, 1, Tmax, hd), jnp.float32)
        q, k, v = mk(), mk(), mk()
        kc = jnp.zeros((1, 1, Tmax, hd), jnp.float32)
        vc = jnp.zeros_like(kc)
        o_chunk, kc1, vc1 = cached_attention(
            q[:, :, :P], k[:, :, :P], v[:, :, :P], kc, vc,
            jnp.zeros((1,)))
        kc2, vc2 = kc, vc
        outs = []
        for t in range(P):
            o, kc2, vc2 = cached_attention(
                q[:, :, t:t + 1], k[:, :, t:t + 1], v[:, :, t:t + 1],
                kc2, vc2, jnp.full((1,), t))
            outs.append(o)
        np.testing.assert_allclose(
            np.asarray(o_chunk), np.asarray(jnp.concatenate(outs, 2)),
            rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(kc1), np.asarray(kc2),
                                   rtol=1e-6, atol=1e-7)

    def test_registered_with_cache_aux(self):
        s = transformer.get_decode_symbol(V, T, num_layers=L,
                                          num_heads=H, dim=DIM)
        aux = s.list_auxiliary_states()
        assert sorted(aux) == sorted(
            ["layer%d_attn_%s" % (i, n)
             for i in range(L) for n in ("k_cache", "v_cache")])
        args = s.list_arguments()
        assert "cache_pos" in args and "positions" in args


class TestTeacherForcingConsistency:
    def test_decode_matches_training_symbol(self):
        """Incremental logits == training-symbol softmax at every
        position (prefill of 4, then token-by-token)."""
        train_sym, params = _trained_params()
        rng = np.random.RandomState(3)
        toks = rng.randint(0, V, (B, T)).astype(np.float32)

        # full forward through the training graph -> per-position probs
        eval_fn = _graph_eval_fn(train_sym)
        raw = {k: getattr(v, "_data", v) for k, v in params.items()}
        labels = np.zeros((B * T,), np.float32)
        outs, _ = eval_fn({**raw, "data": jnp.asarray(toks),
                           "softmax_label": jnp.asarray(labels)},
                          {}, jax.random.PRNGKey(0), False)
        probs_full = np.asarray(outs[0]).reshape(B, T, V)

        # incremental: prefill 4 tokens, then one at a time
        dec = transformer.get_decode_symbol(V, T, num_layers=L,
                                            num_heads=H, dim=DIM)
        dfn = _graph_eval_fn(dec)
        aux = {n: jnp.zeros((B, H, T, DIM // H), jnp.float32)
               for n in dec.list_auxiliary_states()}
        P = 4
        logits_inc = []

        def fwd(chunk, pos):
            nonlocal aux
            tn = chunk.shape[1]
            outs, aux = dfn(
                {**raw, "data": jnp.asarray(chunk),
                 "positions": jnp.arange(pos, pos + tn,
                                         dtype=jnp.float32),
                 "cache_pos": jnp.full((1,), pos, jnp.float32)},
                aux, jax.random.PRNGKey(0), False)
            return np.asarray(outs[0])

        logits_inc.append(fwd(toks[:, :P], 0))
        for t in range(P, T):
            logits_inc.append(fwd(toks[:, t:t + 1], t))
        logits_inc = np.concatenate(logits_inc, axis=1)
        probs_inc = np.asarray(
            jax.nn.softmax(jnp.asarray(logits_inc), axis=-1))
        np.testing.assert_allclose(probs_inc, probs_full,
                                   rtol=1e-4, atol=1e-5)


class TestRoPE:
    def test_rope_op_oracle(self):
        """Rotation matches the hand-rolled complex-multiply form and
        preserves norms."""
        from mxnet_tpu.ops.attention import rope
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(1, 2, 5, 8), jnp.float32)
        pos = jnp.arange(5)
        out = np.asarray(rope(x, pos))
        half = 4
        freqs = 10000.0 ** (-np.arange(half) / half)
        ang = np.arange(5)[:, None] * freqs[None, :]
        x1, x2 = np.asarray(x)[..., :half], np.asarray(x)[..., half:]
        want = np.concatenate(
            [x1 * np.cos(ang) - x2 * np.sin(ang),
             x1 * np.sin(ang) + x2 * np.cos(ang)], axis=-1)
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.linalg.norm(out, axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)

    def test_rope_relative_shift_invariance(self):
        """RoPE attention scores depend only on relative positions:
        shifting all positions by a constant leaves q·k unchanged."""
        from mxnet_tpu.ops.attention import rope
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(1, 1, 6, 16), jnp.float32)
        k = jnp.asarray(rng.randn(1, 1, 6, 16), jnp.float32)

        def scores(shift):
            pos = jnp.arange(6) + shift
            qr, kr = rope(q, pos), rope(k, pos)
            return np.asarray(jnp.einsum("bhqd,bhkd->bhqk", qr, kr))

        np.testing.assert_allclose(scores(0), scores(37),
                                   rtol=1e-4, atol=1e-4)

    def test_rope_teacher_forcing_consistency(self):
        """RoPE decode (rotate-then-cache) reproduces the RoPE training
        forward per position."""
        sym = transformer.get_symbol(V, T, num_layers=L, num_heads=H,
                                     dim=DIM, pos_encoding="rope")
        step = make_train_step(sym, optimizer="sgd")
        state = step.init_state(Xavier(), {"data": (B, T),
                                           "softmax_label": (B, T)})
        params = state[0]
        assert "pos_embed_weight" not in params   # no position table
        raw = {k: getattr(v, "_data", v) for k, v in params.items()}
        rng = np.random.RandomState(6)
        toks = rng.randint(0, V, (B, T)).astype(np.float32)

        eval_fn = _graph_eval_fn(sym)
        outs, _ = eval_fn({**raw, "data": jnp.asarray(toks),
                           "softmax_label": jnp.zeros((B * T,),
                                                      jnp.float32)},
                          {}, jax.random.PRNGKey(0), False)
        probs_full = np.asarray(outs[0]).reshape(B, T, V)

        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B,
                        pos_encoding="rope")
        aux = gen._fresh_aux()
        logits = []
        for t in range(T):
            lg, aux = gen._forward(aux, toks[:, t:t + 1], t)
            logits.append(np.asarray(lg))
        probs_inc = np.asarray(jax.nn.softmax(jnp.asarray(
            np.concatenate(logits, axis=1)), axis=-1))
        np.testing.assert_allclose(probs_inc, probs_full,
                                   rtol=1e-4, atol=1e-5)

    def test_rope_validation(self):
        with pytest.raises(ValueError, match="even head_dim"):
            transformer.get_symbol(V, T, num_heads=2, dim=6,
                                   pos_encoding="rope")
        with pytest.raises(ValueError, match="seq_len"):
            transformer.get_stage_symbol(pos_encoding="rope")
        # a rope stage with seq_len builds fine
        s = transformer.get_stage_symbol(pos_encoding="rope",
                                         seq_len=8, num_heads=2,
                                         dim=16)
        assert "data" in s.list_arguments()

    def test_rope_generates(self):
        sym = transformer.get_symbol(V, T, num_layers=L, num_heads=H,
                                     dim=DIM, pos_encoding="rope")
        step = make_train_step(sym, optimizer="sgd")
        state = step.init_state(Xavier(), {"data": (B, T),
                                           "softmax_label": (B, T)})
        gen = Generator(state[0], V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B,
                        pos_encoding="rope")
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        host = gen.generate(prompt, max_new_tokens=5)
        dev = gen.generate_on_device(prompt, max_new_tokens=5)
        assert (host == dev).all()
        with pytest.raises(ValueError, match="pos_encoding"):
            transformer.get_symbol(V, T, pos_encoding="alibi")


class TestWindowedDecode:
    def test_window_teacher_forcing_consistency(self):
        """Sliding-window decode (banded cache masking) reproduces the
        windowed training forward per position."""
        W = 4
        sym = transformer.get_symbol(V, T, num_layers=L, num_heads=H,
                                     dim=DIM, attention_window=W)
        step = make_train_step(sym, optimizer="sgd")
        state = step.init_state(Xavier(), {"data": (B, T),
                                           "softmax_label": (B, T)})
        raw = {k: getattr(v, "_data", v) for k, v in state[0].items()}
        rng = np.random.RandomState(8)
        toks = rng.randint(0, V, (B, T)).astype(np.float32)

        eval_fn = _graph_eval_fn(sym)
        outs, _ = eval_fn({**raw, "data": jnp.asarray(toks),
                           "softmax_label": jnp.zeros((B * T,),
                                                      jnp.float32)},
                          {}, jax.random.PRNGKey(0), False)
        probs_full = np.asarray(outs[0]).reshape(B, T, V)

        gen = Generator(state[0], V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B,
                        attention_window=W)
        aux = gen._fresh_aux()
        logits = []
        for t in range(T):
            lg, aux = gen._forward(aux, toks[:, t:t + 1], t)
            logits.append(np.asarray(lg))
        probs_inc = np.asarray(jax.nn.softmax(jnp.asarray(
            np.concatenate(logits, axis=1)), axis=-1))
        np.testing.assert_allclose(probs_inc, probs_full,
                                   rtol=1e-4, atol=1e-5)
        # the window genuinely bites: a plain-causal model differs
        sym_c = transformer.get_symbol(V, T, num_layers=L,
                                       num_heads=H, dim=DIM)
        outs_c, _ = _graph_eval_fn(sym_c)(
            {**raw, "data": jnp.asarray(toks),
             "softmax_label": jnp.zeros((B * T,), jnp.float32)},
            {}, jax.random.PRNGKey(0), False)
        assert np.abs(np.asarray(outs_c[0]).reshape(B, T, V)
                      - probs_full).max() > 1e-3


class TestRollingCache:
    def _rope_windowed_params(self, W):
        sym = transformer.get_symbol(V, T, num_layers=L, num_heads=H,
                                     dim=DIM, pos_encoding="rope",
                                     attention_window=W)
        step = make_train_step(sym, optimizer="sgd")
        state = step.init_state(Xavier(), {"data": (B, T),
                                           "softmax_label": (B, T)})
        return state[0]

    def test_rolling_matches_plain_windowed_decode(self):
        """Within the plain cache's reach, a circular cache of capacity
        W+P-1 must produce identical greedy output."""
        W = 4
        params = self._rope_windowed_params(W)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        P = prompt.shape[1]
        plain = Generator(params, V, max_len=T, num_layers=L,
                          num_heads=H, dim=DIM, batch_size=B,
                          pos_encoding="rope", attention_window=W)
        rolling = Generator(params, V, max_len=W + P - 1, num_layers=L,
                            num_heads=H, dim=DIM, batch_size=B,
                            pos_encoding="rope", attention_window=W,
                            rolling_cache=True)
        a = plain.generate(prompt, max_new_tokens=8)
        b = rolling.generate(prompt, max_new_tokens=8)
        assert (a == b).all(), (a, b)

    def test_rolling_generates_past_capacity(self):
        """The point of the circular buffer: generation length far
        beyond the cache capacity (impossible for the plain cache),
        still matching a large-capacity plain run token for token."""
        W = 4
        params = self._rope_windowed_params(W)
        prompt = np.array([[1, 2], [3, 4]])
        P, N = prompt.shape[1], 30          # 32 total >> capacity 5
        rolling = Generator(params, V, max_len=W + P - 1, num_layers=L,
                            num_heads=H, dim=DIM, batch_size=B,
                            pos_encoding="rope", attention_window=W,
                            rolling_cache=True)
        big = Generator(params, V, max_len=P + N, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B,
                        pos_encoding="rope", attention_window=W)
        a = rolling.generate(prompt, max_new_tokens=N)
        b = big.generate(prompt, max_new_tokens=N)
        assert a.shape == (B, P + N)
        assert (a == b).all()

    def test_rolling_validation(self):
        W = 4
        params = self._rope_windowed_params(W)
        gen = Generator(params, V, max_len=W + 1, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B,
                        pos_encoding="rope", attention_window=W,
                        rolling_cache=True)
        with pytest.raises(ValueError, match="rolling cache capacity"):
            gen.generate(np.zeros((B, 4)), max_new_tokens=2)
        with pytest.raises(ValueError, match="rolling_cache needs"):
            transformer.get_decode_symbol(V, 8, rolling_cache=True)
        with pytest.raises(ValueError, match="speculative"):
            gen.generate_speculative(gen, np.zeros((B, 2)), 2)


class TestQuantizedDecode:
    def test_quantized_fc_op_matches_dequant(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 8), jnp.float32)
        w = rng.randn(6, 8).astype(np.float32)
        scale = np.abs(w).max(axis=1) / 127.0
        wq = np.rint(w / scale[:, None]).astype(np.int8)
        b = rng.randn(6).astype(np.float32)
        out = nd._contrib_QuantizedFullyConnected(
            nd.array(np.asarray(x)), nd.array(wq), nd.array(scale),
            nd.array(b), num_hidden=6)
        ref = x @ (wq.astype(np.float32) * scale[:, None]).T + b
        np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_int8_decode_close_to_float(self):
        """Weight-only int8 greedy decode: per-position softmax stays
        close to the float path, weights actually land int8."""
        _, params = _trained_params()
        gen_f = Generator(params, V, max_len=T, num_layers=L,
                          num_heads=H, dim=DIM, batch_size=B)
        gen_q = Generator(params, V, max_len=T, num_layers=L,
                          num_heads=H, dim=DIM, batch_size=B,
                          quantize="int8")
        assert gen_q._params["layer0_qkv_weight"].dtype == jnp.int8
        assert gen_q._params["lm_head_weight"].dtype == jnp.int8
        assert gen_q._params["tok_embed_weight"].dtype == jnp.int8
        assert "layer0_qkv_scale" in gen_q._params
        assert "tok_embed_scale" in gen_q._params

        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        rng_toks = np.random.RandomState(4).randint(
            0, V, (B, 8)).astype(np.float32)
        aux_f = gen_f._fresh_aux()
        aux_q = gen_q._fresh_aux()
        lf, _ = gen_f._forward(aux_f, rng_toks, 0)
        lq, _ = gen_q._forward(aux_q, rng_toks, 0)
        pf = np.asarray(jax.nn.softmax(lf.astype(jnp.float32), -1))
        pq = np.asarray(jax.nn.softmax(lq.astype(jnp.float32), -1))
        assert np.abs(pf - pq).max() < 0.05
        # end-to-end still generates
        out = gen_q.generate(prompt, max_new_tokens=5)
        assert out.shape == (B, 8)

    def test_cache_dtype_ignores_int8_params(self):
        """Param-dict ordering must not leak int8 into the KV caches
        (regression: cache dtype was taken from the dict's first
        entry)."""
        _, params = _trained_params()
        reordered = {"layer0_qkv_weight": params["layer0_qkv_weight"]}
        reordered.update(params)
        gen = Generator(reordered, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B,
                        quantize="int8")
        assert jnp.issubdtype(gen._cache_dtype, jnp.floating)

    def test_quantize_rejects_unknown(self):
        _, params = _trained_params()
        with pytest.raises(ValueError, match="quantize"):
            Generator(params, V, max_len=T, num_layers=L, num_heads=H,
                      dim=DIM, batch_size=B, quantize="fp4")


@pytest.mark.skipif(jax.device_count() < 4,
                    reason="needs a 4-device mesh")
class TestMeshDecode:
    def test_tensor_parallel_greedy_matches_single(self):
        """Generator over a data x model mesh: sharded params + head-
        sharded caches produce the same greedy tokens as one device."""
        from jax.sharding import Mesh
        _, params = _trained_params()
        single = Generator(params, V, max_len=T, num_layers=L,
                           num_heads=H, dim=DIM, batch_size=B)
        mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                    ("data", "model"))
        tp = Generator(params, V, max_len=T, num_layers=L,
                       num_heads=H, dim=DIM, batch_size=B, mesh=mesh)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        a = single.generate(prompt, max_new_tokens=6)
        b = tp.generate(prompt, max_new_tokens=6)
        assert (a == b).all()
        # params actually went down sharded (column-parallel qkv)
        qkv = tp._params["layer0_qkv_weight"]
        assert qkv.sharding.spec[0] == "model"

    def test_on_device_loop_under_mesh(self):
        """The whole-generation lax.scan program also runs with TP
        sharded params + caches and matches the host loop."""
        from jax.sharding import Mesh
        _, params = _trained_params()
        mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                    ("data", "model"))
        tp = Generator(params, V, max_len=T, num_layers=L,
                       num_heads=H, dim=DIM, batch_size=B, mesh=mesh)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        host = tp.generate(prompt, max_new_tokens=5)
        dev = tp.generate_on_device(prompt, max_new_tokens=5)
        assert (host == dev).all()

    def test_int8_composes_with_mesh(self):
        """quantize='int8' + TP mesh: int8 weights shard like float
        ones and decode still runs."""
        from jax.sharding import Mesh
        _, params = _trained_params()
        mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                    ("data", "model"))
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B, mesh=mesh,
                        quantize="int8")
        w = gen._params["layer0_qkv_weight"]
        assert w.dtype == jnp.int8 and w.sharding.spec[0] == "model"
        out = gen.generate(np.array([[1, 2], [3, 4]]),
                           max_new_tokens=3)
        assert out.shape == (B, 5)


class TestMoEDecode:
    def test_moe_teacher_forcing_consistency(self):
        """A Switch-MoE-FFN checkpoint decodes identically to its
        training forward (expert gating runs per appended token)."""
        E = 4
        # capacity raised to E on the training side too: dropping is a
        # training-throughput knob, and a dropped token's FFN output is
        # legitimately zero there while decode always serves it
        sym = transformer.get_symbol(V, T, num_layers=L, num_heads=H,
                                     dim=DIM, num_experts=E,
                                     moe_capacity_factor=E)
        step = make_train_step(sym, optimizer="sgd")
        state = step.init_state(Xavier(),
                                {"data": (B, T),
                                 "softmax_label": (B, T)})
        params = state[0]
        raw = {k: getattr(v, "_data", v) for k, v in params.items()}
        rng = np.random.RandomState(5)
        toks = rng.randint(0, V, (B, T)).astype(np.float32)

        eval_fn = _graph_eval_fn(sym)
        outs, _ = eval_fn({**raw, "data": jnp.asarray(toks),
                           "softmax_label": jnp.zeros((B * T,),
                                                      jnp.float32)},
                          {}, jax.random.PRNGKey(0), False)
        probs_full = np.asarray(outs[0]).reshape(B, T, V)

        dec = transformer.get_decode_symbol(V, T, num_layers=L,
                                            num_heads=H, dim=DIM,
                                            num_experts=E)
        dfn = _graph_eval_fn(dec)
        aux = {n: jnp.zeros((B, H, T, DIM // H), jnp.float32)
               for n in dec.list_auxiliary_states()}
        logits = []
        for t in range(T):
            outs, aux = dfn(
                {**raw, "data": jnp.asarray(toks[:, t:t + 1]),
                 "positions": jnp.full((1,), t, jnp.float32),
                 "cache_pos": jnp.full((1,), t, jnp.float32)},
                aux, jax.random.PRNGKey(0), False)
            logits.append(np.asarray(outs[0]))
        probs_inc = np.asarray(jax.nn.softmax(
            jnp.asarray(np.concatenate(logits, axis=1)), axis=-1))
        np.testing.assert_allclose(probs_inc, probs_full,
                                   rtol=1e-4, atol=1e-5)


class TestGenerator:
    def test_greedy_deterministic_and_shapes(self):
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        out1 = gen.generate(prompt, max_new_tokens=5)
        out2 = gen.generate(prompt, max_new_tokens=5)
        assert out1.shape == (B, 8)
        assert (out1 == out2).all()
        assert (out1[:, :3] == prompt).all()
        assert (out1 >= 0).all() and (out1 < V).all()

    def test_sampling_seeded(self):
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        a = gen.generate(prompt, max_new_tokens=5, temperature=1.0,
                         top_k=5, seed=7)
        b = gen.generate(prompt, max_new_tokens=5, temperature=1.0,
                         top_k=5, seed=7)
        c = gen.generate(prompt, max_new_tokens=5, temperature=1.0,
                         top_k=5, seed=8)
        assert (a == b).all()
        assert a.shape == c.shape

    def test_capacity_and_param_errors(self):
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        with pytest.raises(ValueError, match="exceeds the cache"):
            gen.generate(np.zeros((B, T - 1)), max_new_tokens=2)
        with pytest.raises(ValueError, match="missing parameters"):
            Generator({"tok_embed_weight": np.zeros((V, DIM))}, V,
                      max_len=T, num_layers=L, num_heads=H, dim=DIM)

    def test_on_device_matches_python_loop(self):
        """The lax.scan whole-generation program must emit exactly the
        greedy tokens the per-step python loop emits."""
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        host = gen.generate(prompt, max_new_tokens=6)
        dev = gen.generate_on_device(prompt, max_new_tokens=6)
        assert (host == dev).all()
        # sampled path: deterministic per seed, right shape
        s1 = gen.generate_on_device(prompt, 4, temperature=1.0,
                                    top_k=5, seed=9)
        s2 = gen.generate_on_device(prompt, 4, temperature=1.0,
                                    top_k=5, seed=9)
        assert (s1 == s2).all() and s1.shape == (B, 7)

    def test_beam_w1_equals_greedy(self):
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        greedy = gen.generate(prompt, max_new_tokens=6)
        beam1 = gen.beam_search(prompt, max_new_tokens=6, beam_size=1)
        assert (greedy == beam1).all()

    def test_beam_finds_no_worse_sequence(self):
        """Beam-4's total log-likelihood must be >= greedy's (greedy is
        in beam's search space)."""
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        N = 6

        def seq_logprob(full):
            # score continuation under the training symbol (teacher
            # forcing over the produced sequence)
            sym, _ = _trained_params()
            eval_fn = _graph_eval_fn(sym)
            raw = {k: getattr(v, "_data", v) for k, v in
                   params.items()}
            toks = np.zeros((B, T), np.float32)
            toks[:, :full.shape[1]] = full
            outs, _ = eval_fn(
                {**raw, "data": jnp.asarray(toks),
                 "softmax_label": jnp.zeros((B * T,), jnp.float32)},
                {}, jax.random.PRNGKey(0), False)
            probs = np.asarray(outs[0]).reshape(B, T, V)
            lp = np.zeros(B)
            for b in range(B):
                for t in range(2, 2 + N):   # positions preceding gen
                    nxt = int(full[b, t + 1])
                    lp[b] += np.log(max(probs[b, t, nxt], 1e-9))
            return lp

        greedy = gen.generate(prompt, max_new_tokens=N)
        beam = gen.beam_search(prompt, max_new_tokens=N, beam_size=4)
        lg, lb = seq_logprob(greedy), seq_logprob(beam)
        assert (lb >= lg - 1e-4).all(), (lb, lg)

    def test_beam_eos_freezes(self):
        """With beam_size=1 and eos = the greedy first token, row 0
        freezes at step 1 — every later token MUST be eos (padding by
        the freeze rule), guaranteed non-vacuous."""
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2], [3, 4]])
        greedy = gen.generate(prompt, max_new_tokens=1)
        eos = int(greedy[0, 2])   # row 0's argmax first token
        out = gen.beam_search(prompt, max_new_tokens=6, beam_size=1,
                              eos_id=eos)
        row = out[0, 2:]
        assert row[0] == eos
        assert (row == eos).all()   # frozen: eos continues for free

    def test_gqa_teacher_forcing_consistency(self):
        """Grouped-query attention (num_kv_heads=2, H=4): incremental
        decode must reproduce the training symbol's per-position
        softmax, and the caches must hold only the kv heads."""
        sym_t = transformer.get_symbol(V, T, num_layers=L, num_heads=4,
                                       dim=DIM, num_kv_heads=2)
        step = make_train_step(sym_t, optimizer="sgd")
        mx.random.seed(3)
        params = step.init_state(Xavier(), {"data": (B, T),
                                            "softmax_label": (B, T)})[0]
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=4, dim=DIM, batch_size=B,
                        num_kv_heads=2)
        hd = DIM // 4
        assert gen._cache_shape == (B, 2, T, hd)

        rng = np.random.RandomState(0)
        toks = rng.randint(0, V, (B, T))
        eval_fn = _graph_eval_fn(sym_t)
        raw = {k: getattr(v, "_data", v) for k, v in params.items()}
        outs, _ = eval_fn(
            {**raw, "data": jnp.asarray(toks, jnp.float32),
             "softmax_label": jnp.zeros((B * T,), jnp.float32)},
            {}, jax.random.PRNGKey(0), False)
        want = np.asarray(outs[0]).reshape(B, T, V)

        aux = gen._fresh_aux()
        got = []
        for t in range(T):
            logits, aux = gen._forward(aux, toks[:, t:t + 1], t)
            p = np.asarray(jax.nn.softmax(
                logits[:, -1].astype(jnp.float32), axis=-1))
            got.append(p)
        got = np.stack(got, axis=1)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_gqa_generates_and_validates(self):
        """qkv projection shrinks to (H + 2*Hkv)*hd; generation runs;
        invalid head grouping raises."""
        sym_t = transformer.get_symbol(V, T, num_layers=L, num_heads=4,
                                       dim=DIM, num_kv_heads=1)
        step = make_train_step(sym_t, optimizer="sgd")
        mx.random.seed(4)
        params = step.init_state(Xavier(), {"data": (B, T),
                                            "softmax_label": (B, T)})[0]
        hd = DIM // 4
        w = getattr(params["layer0_qkv_weight"], "_data",
                    params["layer0_qkv_weight"])
        assert w.shape[0] == DIM + 2 * hd      # H*hd + 2*(1*hd)
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=4, dim=DIM, batch_size=B,
                        num_kv_heads=1)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        host = gen.generate(prompt, max_new_tokens=5)
        dev = gen.generate_on_device(prompt, max_new_tokens=5)
        assert host.shape == (B, 8) and (host == dev).all()

        with pytest.raises(ValueError, match="multiple of"):
            transformer.get_symbol(V, T, num_heads=4, num_kv_heads=3)

    def test_speculative_on_device_matches_host_and_greedy(self):
        """The compiled speculative loop (draft scan + verify + accept
        inside lax.while_loop) must emit EXACTLY the target's greedy
        continuation — same contract as the host speculative path."""
        cap = 3 + 8 + 4                            # P + n + lookahead

        def params_with_table(seed):
            sym_t = transformer.get_symbol(V, T, num_layers=L,
                                           num_heads=H, dim=DIM,
                                           max_len=cap)
            step = make_train_step(sym_t, optimizer="sgd")
            mx.random.seed(seed)
            return step.init_state(Xavier(), {
                "data": (B, T), "softmax_label": (B, T)})[0]

        target = Generator(params_with_table(0), V, max_len=cap,
                           num_layers=L, num_heads=H, dim=DIM,
                           batch_size=B)
        draft = Generator(params_with_table(1), V, max_len=cap,
                          num_layers=L, num_heads=H, dim=DIM,
                          batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        greedy = target.generate(prompt, max_new_tokens=8)
        host = target.generate_speculative(draft, prompt, 8,
                                           lookahead=4)
        dev = target.generate_speculative_on_device(draft, prompt, 8,
                                                    lookahead=4)
        assert (host == greedy).all()
        assert (dev == greedy).all(), (dev, greedy)
        # self-drafting: always fully accepts, still exact
        dev2 = target.generate_speculative_on_device(target, prompt,
                                                     8, lookahead=4)
        assert (dev2 == greedy).all()

    def test_speculative_on_device_validates_capacity(self):
        _, t_params = _trained_params(seed=0)
        gen = Generator(t_params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        with pytest.raises(ValueError, match="headroom"):
            gen.generate_speculative_on_device(
                gen, prompt, T - 3, lookahead=4)

    def test_gqa_composes_with_window_and_rolling(self):
        """GQA + RoPE + sliding window + rolling circular caches — the
        full modern-serving composition; rolling caches keep only
        (B, Hkv, C, hd)."""
        sym_t = transformer.get_symbol(V, 24, num_layers=L, num_heads=4,
                                       dim=DIM, num_kv_heads=2,
                                       pos_encoding="rope",
                                       attention_window=8)
        step = make_train_step(sym_t, optimizer="sgd")
        mx.random.seed(5)
        params = step.init_state(Xavier(), {"data": (B, 24),
                                            "softmax_label": (B, 24)})[0]
        gen = Generator(params, V, max_len=12, num_layers=L,
                        num_heads=4, dim=DIM, num_kv_heads=2,
                        batch_size=B, pos_encoding="rope",
                        attention_window=8, rolling_cache=True)
        assert gen._cache_shape == (B, 2, 12, DIM // 4)
        out = gen.generate(np.array([[1, 2, 3], [4, 5, 6]]),
                           max_new_tokens=20)   # past plain capacity
        assert out.shape == (B, 23)

    def test_beam_on_device_matches_host(self):
        """beam_search_on_device (one compiled scan, in-scan cache
        reorder) must reproduce the host-loop beam exactly — tokens
        and W=1/W=4, with and without length penalty."""
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        for w in (1, 4):
            host = gen.beam_search(prompt, max_new_tokens=6,
                                   beam_size=w)
            dev = gen.beam_search_on_device(prompt, max_new_tokens=6,
                                            beam_size=w)
            assert (host == dev).all(), (w, host, dev)
        host = gen.beam_search(prompt, 6, beam_size=4,
                               length_penalty=1.0)
        dev = gen.beam_search_on_device(prompt, 6, beam_size=4,
                                        length_penalty=1.0)
        assert (host == dev).all()

    def test_beam_on_device_eos_freeze(self):
        """eos freezing inside the scan: frozen beams pad with eos at
        no score cost, like the host loop (modulo the host's early
        break — same tokens, fixed length)."""
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2], [3, 4]])
        greedy = gen.generate(prompt, max_new_tokens=1)
        eos = int(greedy[0, 2])
        out = gen.beam_search_on_device(prompt, max_new_tokens=6,
                                        beam_size=1, eos_id=eos)
        assert out.shape == (B, 8)
        row = out[0, 2:]
        assert row[0] == eos and (row == eos).all()

    def test_top_p_sampling(self):
        """Nucleus sampling: seeded determinism; top_p=tiny degenerates
        to greedy (only the argmax survives the nucleus)."""
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        a = gen.generate(prompt, 5, temperature=1.0, top_p=0.9, seed=3)
        b = gen.generate(prompt, 5, temperature=1.0, top_p=0.9, seed=3)
        assert (a == b).all()
        greedy = gen.generate(prompt, 5)
        tiny = gen.generate(prompt, 5, temperature=1.0, top_p=1e-9,
                            seed=11)
        assert (tiny == greedy).all()
        dev = gen.generate_on_device(prompt, 5, temperature=1.0,
                                     top_p=1e-9, seed=11)
        assert (dev == greedy).all()

    def test_log_likelihood(self):
        """Scoring matches a hand-rolled teacher-forcing sum, and the
        greedy continuation scores >= a perturbed one."""
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        toks = np.random.RandomState(9).randint(0, V, (B, 8))
        ll = gen.log_likelihood(toks)
        logits, _ = gen._forward(gen._fresh_aux(), toks, 0)
        lp = np.asarray(jax.nn.log_softmax(
            logits.astype(jnp.float32), -1))
        want = np.zeros(B)
        for b_ in range(B):
            for t in range(7):
                want[b_] += lp[b_, t, toks[b_, t + 1]]
        np.testing.assert_allclose(ll, want, rtol=1e-5, atol=1e-5)

        greedy = gen.generate(toks[:, :3], max_new_tokens=5)
        other = greedy.copy()
        other[:, -1] = (other[:, -1] + 1) % V
        assert (gen.log_likelihood(greedy)
                >= gen.log_likelihood(other) - 1e-6).all()

    def test_bf16_decode(self):
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B,
                        dtype="bfloat16")
        assert gen._cache_dtype == jnp.bfloat16
        out = gen.generate(np.array([[1, 2], [3, 4]]),
                           max_new_tokens=4)
        assert out.shape == (B, 6)

    def test_checkpoint_roundtrip(self, tmp_path):
        """save_checkpoint -> load_checkpoint -> Generator: the
        deployment path the docs promise, end to end."""
        sym, params = _trained_params()
        mod = mx.mod.Module(sym, context=mx.cpu(),
                            label_names=("softmax_label",))
        mod.bind(data_shapes=[("data", (B, T))],
                 label_shapes=[("softmax_label", (B, T))])
        mod.set_params({k: mx.nd.array(np.asarray(
            getattr(v, "_data", v))) for k, v in params.items()}, {},
            allow_missing=False)
        prefix = str(tmp_path / "lm")
        mod.save_checkpoint(prefix, 1)

        _, arg, _ = mx.model.load_checkpoint(prefix, 1)
        gen = Generator(arg, V, max_len=T, num_layers=L, num_heads=H,
                        dim=DIM, batch_size=B)
        direct = Generator(params, V, max_len=T, num_layers=L,
                           num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        assert (gen.generate(prompt, 5)
                == direct.generate(prompt, 5)).all()

    # lookahead=5 re-specializes every draft/verify shape for ~7 s of
    # CPU compile — slow tier; 1 and 3 already span the degenerate and
    # multi-token acceptance paths
    @pytest.mark.parametrize("lookahead",
                             [1, 3,
                              pytest.param(5,
                                           marks=pytest.mark.slow)])
    def test_speculative_equals_greedy(self, lookahead):
        """Speculative output must be EXACTLY the target's greedy
        continuation, for any draft: a weak draft (different seed),
        a perfect draft (the target itself), across lookaheads."""
        _, params = _trained_params()
        target = Generator(params, V, max_len=T, num_layers=L,
                           num_heads=H, dim=DIM, batch_size=B)
        _, params2 = _trained_params(seed=1)
        weak = Generator(params2, V, max_len=T, num_layers=L,
                         num_heads=H, dim=DIM, batch_size=B)
        perfect = Generator(params, V, max_len=T, num_layers=L,
                            num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        want = target.generate(prompt, max_new_tokens=9)
        for draft in (weak, perfect):
            got = target.generate_speculative(
                draft, prompt, max_new_tokens=9, lookahead=lookahead)
            assert (got == want).all(), (lookahead, got, want)

    def test_speculative_perfect_draft_efficiency(self):
        """A perfect draft (the target itself) must accept every
        proposal: ceil(N / (lookahead+1)) verification forwards. This
        is the test that catches draft-cache staleness — a corrupted
        draft cache degrades acceptance, not output."""
        _, params = _trained_params()
        target = Generator(params, V, max_len=T, num_layers=L,
                           num_heads=H, dim=DIM, batch_size=B)
        perfect = Generator(params, V, max_len=T, num_layers=L,
                            num_heads=H, dim=DIM, batch_size=B)
        calls = {"target": 0}
        orig = target._forward

        def counting(aux, tokens, pos):
            calls["target"] += 1
            return orig(aux, tokens, pos)

        target._forward = counting
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        target.generate_speculative(perfect, prompt,
                                    max_new_tokens=8, lookahead=3)
        # 1 prefill + ceil(8/4)=2 verification rounds
        assert calls["target"] == 3, calls

    def test_speculative_validation(self):
        _, params = _trained_params()
        target = Generator(params, V, max_len=T, num_layers=L,
                           num_heads=H, dim=DIM, batch_size=B)
        small = Generator(params, V, max_len=4, num_layers=L,
                          num_heads=H, dim=DIM, batch_size=B)
        with pytest.raises(ValueError, match="draft max_len"):
            target.generate_speculative(small, np.zeros((B, 2)), 6)

    # each on-device case compiles its own (temp, top_k, top_p)
    # specialization of the fused loop — keep the fast tier to the
    # two distinct verification regimes (plain temp, temp+top_k) and
    # ride top_p on the slow tier
    @pytest.mark.parametrize("kw", [
        {"temperature": 0.8, "seed": 0},
        {"temperature": 1.2, "top_k": 5, "seed": 7},
        pytest.param({"temperature": 0.9, "top_p": 0.9, "seed": 3},
                     marks=pytest.mark.slow),
    ])
    def test_speculative_sampled_equals_generate(self, kw):
        """SAMPLED speculative decoding is byte-identical to plain
        generate(seed) — host and compiled paths alike. The contract
        is shared-noise verification (docs/serving.md §speculative):
        emission j is always _pick_token(target_logits_j, sub_j) on
        the request key's (j+1)-th split, the draft merely proposes
        with the same noise — so speculation changes the SCHEDULE,
        never the distribution, and a resumed/failed-over replica
        replays the identical stream."""
        cap = 3 + 8 + 4                        # P + n + lookahead
        sym_t = transformer.get_symbol(V, T, num_layers=L,
                                       num_heads=H, dim=DIM,
                                       max_len=cap)
        step = make_train_step(sym_t, optimizer="sgd")
        mx.random.seed(0)
        params = step.init_state(Xavier(), {
            "data": (B, T), "softmax_label": (B, T)})[0]
        target = Generator(params, V, max_len=cap, num_layers=L,
                           num_heads=H, dim=DIM, batch_size=B)
        draft = target.truncated_draft(num_layers=1)
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        want = target.generate(prompt, max_new_tokens=8, **kw)
        host = target.generate_speculative(draft, prompt, 8,
                                           lookahead=3, **kw)
        dev = target.generate_speculative_on_device(draft, prompt, 8,
                                                    lookahead=3, **kw)
        assert (host == want).all(), (kw, host, want)
        assert (dev == want).all(), (kw, dev, want)

    def test_truncated_draft_shares_params_and_validates(self):
        """truncated_draft: the self-drafting constructor — the
        SHALLOW prefix of the target (same embeddings, first k
        layers, same head) as an independent Generator over the same
        param dict. Depth bounds and unsupported variants fail
        loudly."""
        _, params = _trained_params()
        target = Generator(params, V, max_len=T, num_layers=L,
                           num_heads=H, dim=DIM, batch_size=B)
        draft = target.truncated_draft(num_layers=1)
        assert draft.num_layers == 1
        assert draft.batch_size == target.batch_size
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        want = target.generate(prompt, max_new_tokens=6)
        got = target.generate_speculative(draft, prompt, 6,
                                          lookahead=2)
        assert (got == want).all(), (got, want)
        for bad in (0, L + 1):
            with pytest.raises(ValueError, match="num_layers"):
                target.truncated_draft(num_layers=bad)

    def test_eos_early_stop(self):
        _, params = _trained_params()
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.array([[1, 2], [3, 4]])
        full = gen.generate(prompt, max_new_tokens=6)
        eos = int(full[0, 2])     # force the first greedy pick as eos
        out = gen.generate(prompt, max_new_tokens=6, eos_id=eos)
        assert out.shape[1] <= full.shape[1]
        assert (out[0, 2:] == eos).any()


class TestQuantizedKVCache:
    """quantize_kv=True: int8 k/v caches with per-token scales — the
    serving-bandwidth feature for long-prompt decode. Checks: the op
    is a faithful (to int8) attention, the Generator path stays close
    to the float cache, and a TRAINED model's greedy continuation is
    token-identical (confident logits swallow the quantization
    noise)."""

    def test_q8_op_matches_float_cache(self):
        from mxnet_tpu.ops.attention import cached_attention_q8

        rng = np.random.RandomState(0)
        Tmax, hd = 8, 16
        q = jnp.asarray(rng.randn(1, 2, Tmax, hd), jnp.float32)
        k = jnp.asarray(rng.randn(1, 2, Tmax, hd), jnp.float32)
        v = jnp.asarray(rng.randn(1, 2, Tmax, hd), jnp.float32)
        kc = jnp.zeros((1, 2, Tmax, hd), jnp.int8)
        vc = jnp.zeros_like(kc)
        ks = jnp.zeros((1, 2, Tmax), jnp.float32)
        vs = jnp.zeros_like(ks)
        kcf = jnp.zeros((1, 2, Tmax, hd), jnp.float32)
        vcf = jnp.zeros_like(kcf)
        for t in range(Tmax):
            o8, kc, vc, ks, vs = cached_attention_q8(
                q[:, :, t:t + 1], k[:, :, t:t + 1], v[:, :, t:t + 1],
                kc, vc, ks, vs, jnp.full((1,), t))
            of, kcf, vcf = cached_attention(
                q[:, :, t:t + 1], k[:, :, t:t + 1], v[:, :, t:t + 1],
                kcf, vcf, jnp.full((1,), t))
            # int8 absmax/127 keeps ~2 decimal digits; the softmax
            # weighting keeps the output within ~1%
            np.testing.assert_allclose(np.asarray(o8), np.asarray(of),
                                       rtol=0.05, atol=0.02)
        # the caches really are int8 + per-token scales
        assert kc.dtype == jnp.int8 and vs.dtype == jnp.float32
        assert float(jnp.abs(ks[0, :, :Tmax]).min()) > 0

    def test_q8_generator_close_and_aux_dtypes(self):
        _, params = _trained_params()
        gen8 = Generator(params, V, max_len=T, num_layers=L,
                         num_heads=H, dim=DIM, batch_size=B,
                         quantize_kv=True)
        genf = Generator(params, V, max_len=T, num_layers=L,
                         num_heads=H, dim=DIM, batch_size=B)
        aux = gen8._fresh_aux()
        kinds = {n: a.dtype for n, a in aux.items()}
        assert any(n.endswith("_k_cache") and d == jnp.int8
                   for n, d in kinds.items())
        assert any(n.endswith("_k_scale") and d == jnp.float32
                   for n, d in kinds.items())
        toks = np.arange(B * 6).reshape(B, 6) % V
        l8, _ = gen8._forward(gen8._fresh_aux(), toks, 0)
        lf, _ = genf._forward(genf._fresh_aux(), toks, 0)
        # logits track the float path to quantization tolerance
        np.testing.assert_allclose(np.asarray(l8), np.asarray(lf),
                                   rtol=0.1, atol=0.05)

    @pytest.mark.slow
    def test_q8_trained_greedy_token_identical(self):
        """Train the arithmetic-stride LM (confident logits), then the
        int8-cache greedy continuation must equal the float-cache one
        token for token — the serving-accuracy contract. Slow tier
        (~13 s on the 1-core tier-1 host: it trains a model first);
        the q8 cache keeps fast exactness coverage on untrained params
        above and through the ragged pool in test_serve_decode.py."""
        from tests._lm_utils import arith_corpus

        vocab, Tt, Bt = 16, 12, 8
        sym = transformer.get_symbol(vocab, Tt, num_layers=2,
                                     num_heads=2, dim=32)
        step = make_train_step(sym, optimizer="adam",
                               optimizer_params={"rescale_grad":
                                                 1.0 / Bt})
        state = step.init_state(Xavier(), {"data": (Bt, Tt),
                                           "softmax_label": (Bt, Tt)})
        toks, labels = arith_corpus(Bt, Tt, vocab)
        batch = step.place_batch({"data": toks,
                                  "softmax_label": labels})
        rng = jax.random.PRNGKey(0)
        for _ in range(300):
            state, _outs = step(state, batch, 5e-3, rng)
        params = state[0]

        kw = dict(num_layers=2, num_heads=2, dim=32, batch_size=Bt,
                  max_len=Tt)
        genf = Generator(params, vocab, **kw)
        gen8 = Generator(params, vocab, quantize_kv=True, **kw)
        prompt = toks[:, :4].astype(np.int64)
        outf = genf.generate(prompt, 6)
        out8 = gen8.generate(prompt, 6)
        np.testing.assert_array_equal(outf, out8)
        # and the model really learned the progression (the check has
        # teeth only against a confident model)
        strides = (toks[:, 1] - toks[:, 0]) % vocab
        want = (prompt[:, -1][:, None]
                + strides[:, None] * np.arange(1, 7)) % vocab
        np.testing.assert_array_equal(outf[:, 4:], want)

    def test_q8_composes_with_gqa_and_window(self):
        """The int8 cache must compose with grouped-query heads and
        sliding-window attention (the modes share the cache layout):
        logits track the float path within quantization tolerance."""
        sym = transformer.get_symbol(V, T, num_layers=L, num_heads=4,
                                     dim=DIM, num_kv_heads=2,
                                     attention_window=6)
        step = make_train_step(sym, optimizer="sgd")
        mx.random.seed(7)
        state = step.init_state(Xavier(), {"data": (B, T),
                                           "softmax_label": (B, T)})
        kw = dict(num_layers=L, num_heads=4, dim=DIM, num_kv_heads=2,
                  attention_window=6, batch_size=B, max_len=T)
        gen8 = Generator(state[0], V, quantize_kv=True, **kw)
        genf = Generator(state[0], V, **kw)
        toks = np.arange(B * 8).reshape(B, 8) % V
        l8, _ = gen8._forward(gen8._fresh_aux(), toks, 0)
        lf, _ = genf._forward(genf._fresh_aux(), toks, 0)
        np.testing.assert_allclose(np.asarray(l8), np.asarray(lf),
                                   rtol=0.1, atol=0.05)
        # and generation runs end to end under the combo
        out = gen8.generate(toks[:, :4].astype(np.int64), 4)
        assert out.shape == (B, 8)


class TestEosOnDevice:
    def test_eos_while_loop_matches_host(self):
        """generate_on_device(eos_id=...) — the serving early-stop as a
        while_loop in one program — must emit exactly the host
        generate(eos_id=...) tokens, with finished rows padded by eos
        to the static length (the host truncates instead)."""
        _, params = _trained_params(seed=2)
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.arange(B * 3).reshape(B, 3) % V
        n = 6
        free = gen.generate(prompt, n)           # no-eos greedy probe
        # pick the token some row emits mid-stream so the exit binds
        eos = int(free[0, 4])
        host = gen.generate(prompt, n, eos_id=eos)
        dev = gen.generate_on_device(prompt, n, eos_id=eos)
        assert dev.shape == (B, 3 + n)           # static shape
        # host may truncate once every row finished; token-for-token
        # equality on the emitted region, eos padding after
        np.testing.assert_array_equal(dev[:, :host.shape[1]], host)
        assert np.all(dev[:, host.shape[1]:] == eos)
        # and without eos_id the scan path is unchanged
        np.testing.assert_array_equal(
            gen.generate_on_device(prompt, n), free)

    def test_eos_while_loop_matches_host_sampled(self):
        """The SAMPLED path through the eos while_loop (per-iteration
        key splits + _pick_token inside the carried loop) must track
        host generate() token for token — the scan path's sampled
        parity test doesn't cover this trace."""
        _, params = _trained_params(seed=3)
        gen = Generator(params, V, max_len=T, num_layers=L,
                        num_heads=H, dim=DIM, batch_size=B)
        prompt = np.arange(B * 3).reshape(B, 3) % V
        n = 6
        probe = gen.generate(prompt, n, temperature=1.0, top_k=5,
                             seed=11)
        eos = int(probe[0, 4])
        host = gen.generate(prompt, n, temperature=1.0, top_k=5,
                            eos_id=eos, seed=11)
        dev = gen.generate_on_device(prompt, n, temperature=1.0,
                                     top_k=5, eos_id=eos, seed=11)
        assert dev.shape == (B, 3 + n)
        np.testing.assert_array_equal(dev[:, :host.shape[1]], host)
        assert np.all(dev[:, host.shape[1]:] == eos)
