"""Bench tooling guards: the HLO collective-traffic parser and the
workload catalog (every --network choice must build a symbol)."""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_collective_bytes_parser():
    from bench_scaling import collective_bytes

    txt = "\n".join([
        "%all-reduce.82 = (f32[16,3,3,3]{3,2,1,0}, f32[10]{0}, "
        "/*index=2*/f32[10,64]{1,0}) all-reduce(%a, %b, %c), channel_id=1",
        "%gte = f32[16]{0} get-tuple-element(%all-reduce.82), index=4",
        "%ar2 = f32[8,8]{1,0} all-reduce(%dot.1), channel_id=2",
        "%s = f32[4]{0} all-reduce-start(%x), channel_id=3",
        "%d = f32[4]{0} all-reduce-done(%s)",
        "%ag = bf16[64,32]{1,0} all-gather(%p), dimensions={0}",
        "%rs = f32[16]{0} reduce-scatter(%q), dimensions={0}",
        "%cp = bf16[2,8]{1,0} collective-permute(%r), "
        "source_target_pairs={{0,1}}",
    ])
    got = collective_bytes(txt)
    assert got == {
        # variadic tuple (16*27 + 10 + 640 floats) + plain (64) + async
        # start (4; the -done half must not double count)
        "all-reduce": (16 * 27 + 10 + 640) * 4 + 64 * 4 + 16,
        "all-gather": 64 * 32 * 2,
        "reduce-scatter": 64,
        "collective-permute": 32,
    }, got
    # operand references and non-collective lines contribute nothing
    assert collective_bytes("%x = f32[8]{0} add(%a, %b)") == {}


def test_collective_bytes_on_real_dp_step():
    """End-to-end: the parser must see the grad all-reduce of a real
    dp-sharded train step, sized like the model's parameters."""
    import jax

    import mxnet_tpu as mx
    from bench_scaling import collective_bytes
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.parallel import data_parallel_mesh, make_train_step

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=32)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mesh = data_parallel_mesh()
    step = make_train_step(net, mesh=mesh)
    state = step.init_state(Xavier(), {"data": (16, 8),
                                       "softmax_label": (16,)})
    batch = step.place_batch(
        {"data": np.zeros((16, 8), np.float32),
         "softmax_label": np.zeros((16,), np.float32)})
    txt = step.lower(state, batch, 0.1,
                     jax.random.PRNGKey(0)).compile().as_text()
    got = collective_bytes(txt)
    # fc1: weight (32,8) + bias (32) = 288 floats = 1152 bytes of grads
    assert got.get("all-reduce", 0) >= 288 * 4, got


def test_bench_network_catalog_builds():
    from bench import _IMAGE_NETS

    from mxnet_tpu import models

    for name, (kw, batch, baseline, gmacs, image) in _IMAGE_NETS.items():
        kwargs = dict(kw)
        kwargs.setdefault("num_classes", 1000)
        if kwargs["network"] == "resnet":
            kwargs["image_shape"] = (3, image, image)
        sym = models.get_symbol(**kwargs)
        assert sym.list_outputs(), name
        assert batch > 0 and baseline > 0 and gmacs > 0
        assert image in (224, 299), name
    # inception-v3's baseline/GMACs are 299px figures
    assert _IMAGE_NETS["inception-v3"][4] == 299


def test_bench_fail_exit_code_contract(monkeypatch, capsys):
    """Advisor r4: a tunnel hang must NOT silently promote a stale
    capture into the top-level value with rc=0. Contract: rc=3 for
    hang-under-default-config with last_known attached as a sub-object
    (value null), rc=1 for real failures, and promotion only under the
    explicit BENCH_ALLOW_LAST_KNOWN=1 opt-in."""
    import json

    import pytest

    import bench

    rec = {"value": 123.0, "unit": "img/s", "vs_baseline": 1.1}
    prov = {"file": "bench_out/resnet50.json", "commit": "abc0000",
            "captured": "2026-07-31T00:00:00+00:00"}
    monkeypatch.setattr(bench, "_last_known", lambda m: (rec, prov))
    monkeypatch.setattr(bench, "_DEFAULT_CONFIG", True)
    monkeypatch.delenv("BENCH_ALLOW_LAST_KNOWN", raising=False)

    with pytest.raises(SystemExit) as e:
        bench._fail("resnet50_train_throughput", "backend_init",
                    TimeoutError("tunnel hang"))
    assert e.value.code == 3
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] is None and out["live"] is False
    assert out["last_known"]["value"] == 123.0
    assert out["last_known"]["commit"] == "abc0000"

    # explicit driver opt-in restores the promotion, clearly labeled
    monkeypatch.setenv("BENCH_ALLOW_LAST_KNOWN", "1")
    with pytest.raises(SystemExit) as e:
        bench._fail("resnet50_train_throughput", "backend_init",
                    TimeoutError("tunnel hang"))
    assert e.value.code == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 123.0 and out["source"] == "last_known"
    assert out["live"] is False

    # fast/real failures stay rc=1 even with the opt-in set
    with pytest.raises(SystemExit) as e:
        bench._fail("resnet50_train_throughput", "graph_build",
                    RuntimeError("boom"))
    assert e.value.code == 1
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] is None


def _load_perf_tables():
    import importlib.util
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "perf_tables", os.path.join(repo, "tools", "perf_tables.py"))
    pt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pt)
    return pt


def test_perf_tables_newest_capture_wins(tmp_path):
    """Advisor r4: JSONL captures append chronologically; the rendered
    table must show the LAST record per key, not the first."""
    import json
    pt = _load_perf_tables()
    rec = {"metric": "resnet50_train_throughput", "unit": "img/s",
           "vs_baseline": 1.0, "mfu": 0.2, "step_time_ms": 50.0}
    lines = [dict(rec, value=1000.0), dict(rec, value=2222.0)]
    (tmp_path / "sweep.jsonl").write_text(
        "\n".join(json.dumps(r) for r in lines) + "\n")
    table = pt.training_table(pt.load_records(str(tmp_path)))
    assert "2222" in table and "1000" not in table


def test_perf_tables_renders_from_committed_captures():
    """tools/perf_tables.py turns bench_out/ artifacts into the docs
    tables; must at least render the committed training captures."""
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pt = _load_perf_tables()
    recs = pt.load_records(os.path.join(repo, "bench_out"))
    assert any(r["metric"] == "resnet50_train_throughput"
               for r in recs)
    table = pt.training_table(recs)
    assert "resnet50" in table and "| workload |" in table


def test_perf_tables_excludes_ab_experiment_rows(tmp_path):
    """A/B rows (tools/tpu_ab_regression.sh tags ab_config) measure
    deliberately non-default configs; a newer experiment row must
    never shadow the headline capture."""
    import json
    pt = _load_perf_tables()
    rec = {"metric": "resnet50_train_throughput", "unit": "img/s",
           "vs_baseline": 1.0, "mfu": 0.2, "step_time_ms": 50.0}
    (tmp_path / "resnet50.json").write_text(
        json.dumps(dict(rec, value=2451.0)) + "\n")
    (tmp_path / "ab_regression.jsonl").write_text(
        json.dumps(dict(rec, value=1903.0,
                        ab_config="bn_stats_dot")) + "\n")
    # the jsonl is "newer" on disk
    os.utime(tmp_path / "resnet50.json", (1, 1))
    table = pt.training_table(pt.load_records(str(tmp_path)))
    assert "2451" in table and "1903" not in table


@pytest.mark.gate
def test_bench_killed_mid_run_emits_parseable_stub():
    """ISSUE 12 satellite: a bench killed mid-run BEFORE producing any
    journal/capture must still emit one parseable diagnostic JSON line
    (bench_common.install_death_stub). Deterministic via the
    BENCH_TEST_HANG_AFTER_ARM hook: the bench arms its handlers, tells
    us on stderr, and hangs until we deliver the SIGTERM."""
    import json
    import signal
    import subprocess
    import time

    env = dict(os.environ, BENCH_TEST_HANG_AFTER_ARM="60")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench_serve.py"),
         "--requests", "1"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO)
    try:
        deadline = time.time() + 60
        armed = False
        while time.time() < deadline:
            line = proc.stderr.readline()
            if "BENCH_DEATH_STUB_ARMED" in line:
                armed = True
                break
            if line == "" and proc.poll() is not None:
                break
        assert armed, "bench never armed its death stub"
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 1
    lines = [ln for ln in out.strip().splitlines() if ln.strip()]
    assert lines, "killed bench printed nothing"
    rec = json.loads(lines[-1])          # parseable — the contract
    assert rec["metric"] == "serve_throughput"
    assert rec["value"] is None and rec["live"] is False
    assert "signal" in rec["error"]
    assert rec["signal"] == int(signal.SIGTERM)
    # last_known rides along when a committed serve capture exists
    # (none is committed until the first live tunnel window) — when it
    # does, it must stay a sub-object, never promoted
    if "last_known" in rec:
        assert rec["value"] is None


def test_bench_last_known_excludes_experiment_rows():
    """bench.py's outage fallback shares is_experiment_row: against
    the REAL committed bench_out (which contains ab_regression.jsonl
    rows committed AFTER the headline), _last_known must still cite
    the headline artifact, not a deliberately-slowed A/B row."""
    import importlib.util
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(repo, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    rec, prov = bench._last_known("resnet50_train_throughput")
    assert rec is not None
    assert not rec.get("ab_config")
    assert prov["file"].endswith("resnet50.json")
