"""SSD workload smoke (BASELINE config #5 shape): mini SSD trains
end-to-end — ImageDetIter -> MultiBoxPrior/Target heads -> Module-style
forward/backward/update — and the training loss decreases.

Reference: example/ssd/train/train_net.py + symbol/symbol_builder.py.
"""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io, nd, recordio
from mxnet_tpu import sym as S
from mxnet_tpu import image as img_mod


def _mini_ssd_symbol(num_classes=3, num_anchor_shapes=3):
    """Tiny SSD train graph: one feature map, one anchor set."""
    data = S.Variable("data")
    label = S.Variable("label")

    c1 = S.Activation(S.Convolution(data, name="c1", num_filter=8,
                                    kernel=(3, 3), stride=(2, 2),
                                    pad=(1, 1)), act_type="relu")
    feat = S.Activation(S.Convolution(c1, name="c2", num_filter=16,
                                      kernel=(3, 3), stride=(2, 2),
                                      pad=(1, 1)), act_type="relu")

    K, C = num_anchor_shapes, num_classes + 1
    cls_head = S.Convolution(feat, name="cls_head", num_filter=K * C,
                             kernel=(3, 3), pad=(1, 1))
    loc_head = S.Convolution(feat, name="loc_head", num_filter=K * 4,
                             kernel=(3, 3), pad=(1, 1))

    # (B, K*C, H, W) -> (B, C, A): channel-last flatten then class split
    cls_pred = S.transpose(cls_head, axes=(0, 2, 3, 1))
    cls_pred = S.reshape(S.Flatten(cls_pred), shape=(0, -1, C))
    cls_pred = S.transpose(cls_pred, axes=(0, 2, 1))
    loc_pred = S.Flatten(S.transpose(loc_head, axes=(0, 2, 3, 1)))

    anchors = S._contrib_MultiBoxPrior(feat, sizes=(0.3, 0.6),
                                       ratios=(1.0, 2.0), clip=True)
    tgt = S._contrib_MultiBoxTarget(anchors, label, cls_pred,
                                    overlap_threshold=0.4,
                                    negative_mining_ratio=3.0)
    loc_target, loc_mask, cls_target = tgt[0], tgt[1], tgt[2]

    cls_prob = S.SoftmaxOutput(cls_pred, cls_target, multi_output=True,
                               use_ignore=True, ignore_label=-1.0,
                               normalization="valid", name="cls_prob")
    loc_diff = loc_mask * (loc_pred - loc_target)
    loc_loss = S.MakeLoss(S.smooth_l1(loc_diff, scalar=1.0),
                          grad_scale=1.0, normalization="valid",
                          name="loc_loss")
    return S.Group([cls_prob, loc_loss, S.BlockGrad(cls_target)])


def _make_det_rec(tmp, n=16, size=32):
    rec = os.path.join(tmp, "ssd.rec")
    idx = os.path.join(tmp, "ssd.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        cls = i % 3
        img = np.full((size, size, 3), 30 * (cls + 1), np.uint8)
        img += rng.randint(0, 20, img.shape).astype(np.uint8)
        # one box per image, class-dependent position
        box = [0.1 + 0.2 * cls, 0.2, 0.4 + 0.2 * cls, 0.7]
        label = np.array([2, 5, cls, *box], np.float32)
        packed = recordio.pack_img(recordio.IRHeader(0, label, i, 0),
                                   img, img_fmt=".png")
        w.write_idx(i, packed)
    w.close()
    return rec


def test_ssd_trains_end_to_end():
    # deterministic init regardless of suite order (the convergence gate
    # is sensitive to the Xavier draw)
    np.random.seed(0)
    mx.random.seed(0)
    batch = 8
    with tempfile.TemporaryDirectory() as tmp:
        rec = _make_det_rec(tmp, n=16)
        it = img_mod.ImageDetIter(batch_size=batch,
                                  data_shape=(3, 32, 32),
                                  path_imgrec=rec)
        train_sym = _mini_ssd_symbol()

        mod = mx.mod.Module(train_sym, data_names=("data",),
                            label_names=("label",), context=mx.cpu())
        first = next(it)
        it.reset()
        mod.bind(data_shapes=[("data", first.data[0].shape)],
                 label_shapes=[("label", first.label[0].shape)])
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "momentum": 0.9})

        losses = []
        for epoch in range(6):
            it.reset()
            total, count = 0.0, 0
            for b in it:
                mod.forward(b, is_train=True)
                cls_prob, loc_loss, cls_target = \
                    [o.asnumpy() for o in mod.get_outputs()]
                mod.backward()
                mod.update()
                # monitored loss: cls NLL over non-ignored anchors + loc
                tgt = cls_target.astype(int)
                valid = tgt >= 0
                b_idx, a_idx = np.nonzero(valid)
                p = cls_prob[b_idx, tgt[b_idx, a_idx], a_idx]
                nll = -np.log(np.maximum(p, 1e-9)).mean()
                total += nll + loc_loss.sum()
                count += 1
            losses.append(total / count)

        assert np.isfinite(losses).all(), losses
        assert losses[-1] < losses[0] * 0.7, losses

        # detection inference path over the trained weights
        arg, aux = mod.get_params()
        infer_data = S.Variable("data")
        # rebuild heads for inference reusing weights by name
        test_sym = _mini_ssd_symbol()
        # run detection from the train graph's pieces eagerly instead:
        it.reset()
        b = next(it)
        mod.forward(b, is_train=False)
        cls_prob = mod.get_outputs()[0]
        feat_anchors = nd._contrib_MultiBoxPrior(
            nd.zeros((1, 16, 8, 8)), sizes=(0.3, 0.6), ratios=(1.0, 2.0),
            clip=True)
        # loc_pred from a fresh forward of the loc head is inside the
        # graph; use zeros to at least exercise the op end-to-end
        det = nd._contrib_MultiBoxDetection(
            cls_prob, nd.zeros((batch, feat_anchors.shape[1] * 4)),
            feat_anchors, nms_threshold=0.45)
        assert det.shape == (batch, feat_anchors.shape[1], 6)
