"""Tests for the spatial-warp op family, LibSVMIter, and the
predict/export path (reference: test_operator.py bilinear/spatial/
correlation blocks, iter_libsvm.cc, c_predict_api.cc)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


class TestGridGenerator:
    def test_identity_affine(self):
        theta = nd.array(np.array([[1, 0, 0, 0, 1, 0]], "float32"))
        grid = nd.GridGenerator(theta, transform_type="affine",
                                target_shape=(3, 4)).asnumpy()
        assert grid.shape == (1, 2, 3, 4)
        np.testing.assert_allclose(grid[0, 0, 0], [-1, -1 / 3, 1 / 3, 1],
                                   atol=1e-6)
        np.testing.assert_allclose(grid[0, 1, :, 0], [-1, 0, 1],
                                   atol=1e-6)

    def test_warp_zero_flow_is_identity_grid(self):
        flow = nd.zeros((1, 2, 3, 3))
        grid = nd.GridGenerator(flow, transform_type="warp").asnumpy()
        np.testing.assert_allclose(grid[0, 0, 0], [-1, 0, 1], atol=1e-6)


class TestBilinearSampler:
    def test_identity_grid_reproduces_input(self):
        data = np.random.RandomState(0).randn(2, 3, 5, 4).astype("float32")
        theta = np.tile(np.array([[1, 0, 0, 0, 1, 0]], "float32"), (2, 1))
        grid = nd.GridGenerator(nd.array(theta), transform_type="affine",
                                target_shape=(5, 4))
        out = nd.BilinearSampler(nd.array(data), grid).asnumpy()
        np.testing.assert_allclose(out, data, rtol=1e-5, atol=1e-5)

    def test_outside_samples_are_zero(self):
        data = nd.ones((1, 1, 2, 2))
        grid = nd.array(np.full((1, 2, 2, 2), 5.0, "float32"))
        out = nd.BilinearSampler(data, grid).asnumpy()
        assert np.all(out == 0)

    def test_interpolation_midpoint(self):
        data = nd.array(np.array([[[[0., 1.], [2., 3.]]]], "float32"))
        grid = nd.array(np.zeros((1, 2, 1, 1), "float32"))  # center
        out = nd.BilinearSampler(data, grid).asnumpy()
        np.testing.assert_allclose(out[0, 0, 0, 0], 1.5, rtol=1e-6)

    def test_gradients_flow(self):
        data = nd.array(np.random.randn(1, 2, 4, 4).astype("float32"))
        grid = nd.array(
            np.random.uniform(-0.9, 0.9, (1, 2, 3, 3)).astype("float32"))
        data.attach_grad()
        grid.attach_grad()
        with mx.autograd.record():
            out = nd.BilinearSampler(data, grid)
        out.backward()
        assert np.abs(data.grad.asnumpy()).sum() > 0
        assert grid.grad is not None


class TestSpatialTransformer:
    def test_matches_grid_plus_sampler(self):
        rng = np.random.RandomState(1)
        data = rng.randn(2, 3, 6, 6).astype("float32")
        theta = rng.uniform(-1, 1, (2, 6)).astype("float32")
        st = nd.SpatialTransformer(
            nd.array(data), nd.array(theta), target_shape=(4, 5),
            transform_type="affine", sampler_type="bilinear").asnumpy()
        grid = nd.GridGenerator(nd.array(theta), transform_type="affine",
                                target_shape=(4, 5))
        two = nd.BilinearSampler(nd.array(data), grid).asnumpy()
        np.testing.assert_allclose(st, two, rtol=1e-6)


class TestCorrelation:
    def test_self_correlation_zero_displacement(self):
        rng = np.random.RandomState(2)
        a = rng.randn(1, 4, 6, 6).astype("float32")
        out = nd.Correlation(nd.array(a), nd.array(a), kernel_size=1,
                             max_displacement=1, stride1=1, stride2=1,
                             pad_size=1).asnumpy()
        assert out.shape == (1, 9, 6, 6)
        # center channel (zero displacement) == mean over C of a*a
        center = out[0, 4]
        np.testing.assert_allclose(center, (a[0] ** 2).mean(0), rtol=1e-5)

    def test_displacement_picks_up_shift(self):
        a = np.zeros((1, 1, 5, 5), "float32")
        b = np.zeros((1, 1, 5, 5), "float32")
        a[0, 0, 2, 2] = 1.0
        b[0, 0, 2, 3] = 1.0   # b is a shifted right by 1
        out = nd.Correlation(nd.array(a), nd.array(b), kernel_size=1,
                             max_displacement=1, pad_size=1).asnumpy()
        # displacement (dy=0, dx=+1) is channel 5 in the 3x3 grid
        assert out[0, 5, 2, 2] == 1.0
        assert out[0, 4].max() == 0.0


class TestLibSVMIter:
    def test_reads_and_batches(self, tmp_path):
        path = str(tmp_path / "train.libsvm")
        with open(path, "w") as f:
            f.write("1 0:1.5 3:2.0\n")
            f.write("0 1:1.0\n")
            f.write("1 2:3.0 3:4.0\n")
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(4,),
                              batch_size=2)
        batches = list(it)
        assert len(batches) == 2
        b0 = batches[0]
        assert b0.data[0].stype == "csr"
        np.testing.assert_allclose(
            b0.data[0].asnumpy(),
            [[1.5, 0, 0, 2.0], [0, 1.0, 0, 0]])
        np.testing.assert_array_equal(b0.label[0].asnumpy(), [1, 0])
        # wrap-around padding on the last batch
        b1 = batches[1]
        assert b1.pad == 1
        np.testing.assert_allclose(
            b1.data[0].asnumpy(),
            [[0, 0, 3.0, 4.0], [1.5, 0, 0, 2.0]])

    def test_label_libsvm_multidim(self, tmp_path):
        data = str(tmp_path / "d.libsvm")
        lab = str(tmp_path / "l.libsvm")
        with open(data, "w") as f:
            f.write("0 0:1.0\n0 1:1.0\n")
        with open(lab, "w") as f:
            f.write("0 0:1.0 2:5.0\n")
            f.write("0 1:2.0\n")
        it = mx.io.LibSVMIter(data_libsvm=data, data_shape=(2,),
                              batch_size=2, label_libsvm=lab,
                              label_shape=(3,))
        assert it.provide_label[0].shape == (2, 3)
        batch = next(it)
        np.testing.assert_allclose(batch.label[0].asnumpy(),
                                   [[1, 0, 5], [0, 2, 0]])

    def test_label_shape_without_file_rejected(self, tmp_path):
        data = str(tmp_path / "d2.libsvm")
        with open(data, "w") as f:
            f.write("0 0:1.0\n")
        with pytest.raises(ValueError):
            mx.io.LibSVMIter(data_libsvm=data, data_shape=(2,),
                             batch_size=1, label_shape=(3,))

    def test_sparse_dot_training_flow(self, tmp_path):
        """csr batch drives a linear model through sparse dot."""
        rng = np.random.RandomState(3)
        path = str(tmp_path / "w.libsvm")
        w_true = rng.randn(10).astype("float32")
        with open(path, "w") as f:
            for _ in range(8):
                cols = np.sort(rng.choice(10, 3, replace=False))
                vals = rng.randn(3)
                label = float((vals * w_true[cols]).sum() > 0)
                f.write("%d %s\n" % (label, " ".join(
                    "%d:%.4f" % (c, v) for c, v in zip(cols, vals))))
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(10,),
                              batch_size=4)
        batch = next(it)
        w = nd.array(rng.randn(10, 1).astype("float32"))
        out = nd.dot(batch.data[0], w)
        assert out.shape == (4, 1)


class TestPredictor:
    def _train_tiny(self, tmp_path):
        np.random.seed(0)
        X = np.random.randn(64, 6).astype("float32")
        y = (X.sum(1) > 0).astype("float32")
        net = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                  name="fc"), name="softmax")
        mod = mx.mod.Module(net, ("data",), ("softmax_label",))
        train = mx.io.NDArrayIter(X, y, batch_size=16)
        mod.fit(train, num_epoch=2, optimizer="sgd",
                optimizer_params={"learning_rate": 0.5})
        prefix = str(tmp_path / "m")
        mod.save_checkpoint(prefix, 1)
        return prefix, X

    def test_checkpoint_predictor(self, tmp_path):
        prefix, X = self._train_tiny(tmp_path)
        pred = mx.predictor.load_checkpoint_predictor(prefix, 1)
        out = pred.forward(data=X[:8])
        assert out[0].shape == (8, 2)
        np.testing.assert_allclose(out[0].asnumpy().sum(1), np.ones(8),
                                   rtol=1e-5)

    def test_export_and_headless_reload(self, tmp_path):
        prefix, X = self._train_tiny(tmp_path)
        pred = mx.predictor.load_checkpoint_predictor(prefix, 1)
        want = pred.forward(data=X[:8])[0].asnumpy()

        art = pred.export(str(tmp_path / "deploy"),
                          {"data": (8, 6)})
        assert os.path.exists(art)
        loaded = mx.predictor.CompiledPredictor.load(
            str(tmp_path / "deploy"))
        got = loaded.forward(data=X[:8])[0].asnumpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert loaded.output_names == ["softmax_output"]
