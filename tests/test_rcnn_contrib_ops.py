"""Contrib tail + RCNN op family tests (reference:
src/operator/contrib/*, tests/python/unittest/test_operator.py
quantize/fft blocks and the rcnn example semantics)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


class TestFFT:
    def test_fft_matches_numpy(self):
        x = np.random.RandomState(0).randn(3, 8).astype("float32")
        out = nd._contrib_fft(nd.array(x)).asnumpy()
        ref = np.fft.fft(x, axis=-1)
        inter = np.stack([ref.real, ref.imag], -1).reshape(3, 16)
        np.testing.assert_allclose(out, inter, rtol=1e-4, atol=1e-4)

    def test_ifft_unnormalized_roundtrip(self):
        x = np.random.RandomState(1).randn(2, 8).astype("float32")
        freq = nd._contrib_fft(nd.array(x))
        back = nd._contrib_ifft(freq).asnumpy()
        np.testing.assert_allclose(back, x * 8, rtol=1e-4, atol=1e-4)


class TestCountSketch:
    def test_matches_numpy(self):
        rng = np.random.RandomState(2)
        in_dim, out_dim = 10, 6
        x = rng.randn(4, in_dim).astype("float32")
        h = rng.randint(0, out_dim, (1, in_dim)).astype("float32")
        s = rng.choice([-1.0, 1.0], (1, in_dim)).astype("float32")
        out = nd._contrib_count_sketch(nd.array(x), nd.array(h),
                                       nd.array(s),
                                       out_dim=out_dim).asnumpy()
        ref = np.zeros((4, out_dim), "float32")
        for j in range(in_dim):
            ref[:, int(h[0, j])] += s[0, j] * x[:, j]
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


class TestQuantize:
    def test_roundtrip(self):
        x = np.random.RandomState(3).uniform(-2, 3, (4, 5)) \
            .astype("float32")
        q, qmin, qmax = nd._contrib_quantize(
            nd.array(x), nd.array([-2.0]), nd.array([3.0]))
        assert q.asnumpy().dtype == np.uint8
        back = nd._contrib_dequantize(q, qmin, qmax).asnumpy()
        np.testing.assert_allclose(back, x, atol=(3 + 2) / 255 + 1e-6)

    def test_int8_roundtrip(self):
        x = np.random.RandomState(4).uniform(-2, 3, (4, 5)) \
            .astype("float32")
        q, qmin, qmax = nd._contrib_quantize(
            nd.array(x), nd.array([-2.0]), nd.array([3.0]),
            out_type="int8")
        qn = q.asnumpy()
        assert qn.dtype == np.int8
        assert qn.min() < 0 and qn.max() > 64   # both halves used
        back = nd._contrib_dequantize(q, qmin, qmax,
                                      out_type="float32").asnumpy()
        np.testing.assert_allclose(back, x, atol=(3 + 2) / 254 + 1e-6)


def _np_proposal_oracle(cls_prob, bbox_pred, im_info, fs, scales, ratios,
                        pre_n, post_n, thr, min_size):
    from mxnet_tpu.ops.rcnn_ops import _shifted_anchors
    B, twoA, H, W = cls_prob.shape
    A = twoA // 2
    anchors = _shifted_anchors(H, W, fs, scales, ratios)
    out = []
    for b in range(B):
        scores = cls_prob[b, A:].transpose(1, 2, 0).reshape(-1)
        deltas = bbox_pred[b].reshape(A, 4, H, W).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        w = anchors[:, 2] - anchors[:, 0] + 1
        h = anchors[:, 3] - anchors[:, 1] + 1
        cx = anchors[:, 0] + 0.5 * (w - 1)
        cy = anchors[:, 1] + 0.5 * (h - 1)
        pcx = deltas[:, 0] * w + cx
        pcy = deltas[:, 1] * h + cy
        pw = np.exp(deltas[:, 2]) * w
        ph = np.exp(deltas[:, 3]) * h
        boxes = np.stack([
            np.clip(pcx - 0.5 * (pw - 1), 0, im_info[b, 1] - 1),
            np.clip(pcy - 0.5 * (ph - 1), 0, im_info[b, 0] - 1),
            np.clip(pcx + 0.5 * (pw - 1), 0, im_info[b, 1] - 1),
            np.clip(pcy + 0.5 * (ph - 1), 0, im_info[b, 0] - 1)], 1)
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        ms = min_size * im_info[b, 2]
        scores = np.where((ws >= ms) & (hs >= ms), scores, -np.inf)
        order = np.argsort(-scores, kind="stable")[:pre_n]
        sb, ss = boxes[order], scores[order]
        keep = []
        for i in range(len(sb)):
            if ss[i] == -np.inf:
                continue
            ok = True
            for j in keep:
                ix1 = max(sb[i, 0], sb[j, 0])
                iy1 = max(sb[i, 1], sb[j, 1])
                ix2 = min(sb[i, 2], sb[j, 2])
                iy2 = min(sb[i, 3], sb[j, 3])
                iw = max(ix2 - ix1 + 1, 0)
                ih = max(iy2 - iy1 + 1, 0)
                inter = iw * ih
                a_i = (sb[i, 2] - sb[i, 0] + 1) * (sb[i, 3] - sb[i, 1] + 1)
                a_j = (sb[j, 2] - sb[j, 0] + 1) * (sb[j, 3] - sb[j, 1] + 1)
                if inter / (a_i + a_j - inter) > thr:
                    ok = False
                    break
            if ok:
                keep.append(i)
        rows = [np.concatenate([[b], sb[k]]) for k in keep[:post_n]]
        while len(rows) < post_n:
            rows.append(np.concatenate([[b], sb[0]]))
        out.extend(rows)
    return np.asarray(out, "float32")


class TestProposal:
    def test_matches_numpy_oracle(self):
        rng = np.random.RandomState(4)
        B, A, H, W = 2, 3, 4, 4
        scales, ratios, fs = (8.0,), (0.5, 1.0, 2.0), 16
        cls_prob = rng.uniform(0, 1, (B, 2 * A, H, W)).astype("float32")
        bbox_pred = (rng.randn(B, 4 * A, H, W) * 0.1).astype("float32")
        im_info = np.array([[64, 64, 1.0], [64, 64, 1.0]], "float32")
        out = nd._contrib_MultiProposal(
            nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
            rpn_pre_nms_top_n=30, rpn_post_nms_top_n=8, threshold=0.7,
            rpn_min_size=4, scales=scales, ratios=ratios,
            feature_stride=fs).asnumpy()
        ref = _np_proposal_oracle(cls_prob, bbox_pred, im_info, fs,
                                  scales, ratios, 30, 8, 0.7, 4)
        assert out.shape == (2 * 8, 5)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-3)

    def test_post_nms_exceeds_candidates_pads(self):
        """Default rpn_post_nms_top_n=300 on a tiny feature map must pad,
        not crash."""
        rng = np.random.RandomState(9)
        cls_prob = rng.uniform(0, 1, (1, 6, 4, 4)).astype("float32")
        bbox_pred = np.zeros((1, 12, 4, 4), "float32")
        im_info = np.array([[64, 64, 1.0]], "float32")
        out = nd._contrib_MultiProposal(
            nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
            scales=(8.0,), ratios=(0.5, 1.0, 2.0),
            rpn_min_size=2).asnumpy()
        assert out.shape == (300, 5)
        assert np.isfinite(out).all()

    def test_proposal_single_image_with_scores(self):
        rng = np.random.RandomState(5)
        cls_prob = rng.uniform(0, 1, (1, 6, 3, 3)).astype("float32")
        bbox_pred = np.zeros((1, 12, 3, 3), "float32")
        im_info = np.array([[48, 48, 1.0]], "float32")
        rois, scores = nd._contrib_Proposal(
            nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
            rpn_pre_nms_top_n=20, rpn_post_nms_top_n=5,
            rpn_min_size=2, scales=(4.0,), ratios=(0.5, 1.0, 2.0),
            feature_stride=16, output_score=True)
        assert rois.shape == (5, 5) and scores.shape == (5, 1)


class TestPSROIPooling:
    def test_group_channel_selection(self):
        """Channel c*G²+k holds the constant (c*G²+k); bin (i,j) of
        output channel c must read k = i*G + j exactly."""
        out_dim, G = 2, 3
        C = out_dim * G * G
        data = np.zeros((1, C, 12, 12), "float32")
        for c in range(C):
            data[0, c] = c
        rois = np.array([[0, 0, 0, 11, 11]], "float32")
        out = nd._contrib_PSROIPooling(
            nd.array(data), nd.array(rois), spatial_scale=1.0,
            output_dim=out_dim, pooled_size=G).asnumpy()
        assert out.shape == (1, out_dim, G, G)
        for c in range(out_dim):
            for i in range(G):
                for j in range(G):
                    assert out[0, c, i, j] == pytest.approx(
                        c * G * G + i * G + j, abs=1e-4)

    def test_deformable_zero_trans_matches_plain(self):
        rng = np.random.RandomState(6)
        data = rng.randn(1, 2 * 4, 8, 8).astype("float32")
        rois = np.array([[0, 1, 1, 6, 6], [0, 0, 0, 7, 7]], "float32")
        plain = nd._contrib_PSROIPooling(
            nd.array(data), nd.array(rois), spatial_scale=0.5,
            output_dim=2, pooled_size=2).asnumpy()
        # trans is PER ROI: (R, 2, part, part)
        trans = np.zeros((2, 2, 2, 2), "float32")
        deform = nd._contrib_DeformablePSROIPooling(
            nd.array(data), nd.array(rois), nd.array(trans),
            spatial_scale=0.5, output_dim=2, pooled_size=2,
            trans_std=0.1).asnumpy()
        np.testing.assert_allclose(plain, deform, rtol=1e-5)

    def test_per_roi_trans_offsets_differ(self):
        """Each ROI reads its own offset grid (reference indexes
        bottom_trans by roi ordinal, not image)."""
        rng = np.random.RandomState(7)
        data = rng.randn(1, 1 * 4, 8, 8).astype("float32")
        rois = np.array([[0, 1, 1, 6, 6], [0, 1, 1, 6, 6]], "float32")
        trans = np.zeros((2, 2, 2, 2), "float32")
        trans[1] = 0.5           # only ROI 1 shifts
        out = nd._contrib_DeformablePSROIPooling(
            nd.array(data), nd.array(rois), nd.array(trans),
            spatial_scale=1.0, output_dim=1, pooled_size=2,
            trans_std=0.5).asnumpy()
        assert not np.allclose(out[0], out[1])


class TestDeformableConv:
    def test_zero_offset_matches_convolution(self):
        rng = np.random.RandomState(7)
        data = rng.randn(2, 4, 7, 7).astype("float32")
        weight = rng.randn(6, 4, 3, 3).astype("float32")
        bias = rng.randn(6).astype("float32")
        offset = np.zeros((2, 2 * 9, 7, 7), "float32")
        out = nd._contrib_DeformableConvolution(
            nd.array(data), nd.array(offset), nd.array(weight),
            nd.array(bias), kernel=(3, 3), pad=(1, 1),
            num_filter=6).asnumpy()
        ref = nd.Convolution(nd.array(data), nd.array(weight),
                             nd.array(bias), kernel=(3, 3), pad=(1, 1),
                             num_filter=6).asnumpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_integer_offset_shifts_sampling(self):
        data = np.zeros((1, 1, 5, 5), "float32")
        data[0, 0, 2, 3] = 1.0
        weight = np.ones((1, 1, 1, 1), "float32")
        # offset dx=+1 everywhere: a 1x1 kernel reads position x+1
        offset = np.zeros((1, 2, 5, 5), "float32")
        offset[0, 1] = 1.0
        out = nd._contrib_DeformableConvolution(
            nd.array(data), nd.array(offset), nd.array(weight),
            kernel=(1, 1), num_filter=1, no_bias=True).asnumpy()
        assert out[0, 0, 2, 2] == 1.0
        assert out[0, 0, 2, 3] == 0.0

    def test_gradients_flow(self):
        rng = np.random.RandomState(8)
        data = nd.array(rng.randn(1, 2, 5, 5).astype("float32"))
        offset = nd.array(
            (rng.randn(1, 2 * 4, 4, 4) * 0.1).astype("float32"))
        weight = nd.array(rng.randn(3, 2, 2, 2).astype("float32"))
        for a in (data, offset, weight):
            a.attach_grad()
        with mx.autograd.record():
            out = nd._contrib_DeformableConvolution(
                data, offset, weight, kernel=(2, 2), num_filter=3,
                no_bias=True)
        out.backward()
        assert np.abs(data.grad.asnumpy()).sum() > 0
        assert np.abs(offset.grad.asnumpy()).sum() > 0
        assert np.abs(weight.grad.asnumpy()).sum() > 0
