"""Config knob registry (mxnet_tpu/config.py — the dmlc::GetEnv
analogue)."""
import os

import pytest

import mxnet_tpu as mx
from mxnet_tpu import config


def test_declared_knobs_documented():
    rows = config.describe()
    names = [r[0] for r in rows]
    assert "MXNET_MATMUL_PRECISION" in names
    assert "MXNET_BACKWARD_DO_MIRROR" in names
    assert all(r[3] for r in rows), "every knob needs a docstring"


def test_env_and_override_precedence(monkeypatch):
    monkeypatch.setenv("MXNET_NATIVE_RECORDIO", "0")
    assert config.get("MXNET_NATIVE_RECORDIO") is False
    config.set_override("MXNET_NATIVE_RECORDIO", "yes")
    try:
        assert config.get("MXNET_NATIVE_RECORDIO") is True
    finally:
        config.clear_override("MXNET_NATIVE_RECORDIO")
    assert config.get("MXNET_NATIVE_RECORDIO") is False


def test_bool_coercion_rejects_junk(monkeypatch):
    monkeypatch.setenv("MXNET_PROFILER_AUTOSTART", "maybe")
    with pytest.raises(ValueError):
        config.get("MXNET_PROFILER_AUTOSTART")


def test_env_flag_routes_through_config():
    from mxnet_tpu.base import env_flag
    config.set_override("MXNET_BACKWARD_DO_MIRROR", "1")
    try:
        assert env_flag("MXNET_BACKWARD_DO_MIRROR") is True
    finally:
        config.clear_override("MXNET_BACKWARD_DO_MIRROR")
    assert env_flag("MXNET_BACKWARD_DO_MIRROR") is False


def test_conflicting_redefine_rejected():
    with pytest.raises(ValueError):
        config.define("MXNET_NATIVE_RECORDIO", str, "nope", "conflict")
