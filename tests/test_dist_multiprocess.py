"""Multi-process-on-one-host distributed test — the SURVEY §4 pattern
(reference tests/nightly/dist_sync_kvstore.py launched with the `local`
dmlc_tracker): two local processes form a cluster via the DMLC_* env
shim (parallel/dist.py) and run a real cross-process collective.
"""
import os
import socket
import subprocess
import sys

import pytest

_WORKER_SRC = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.parallel import dist
import jax, jax.numpy as jnp

dist.init()
n = int(os.environ["DMLC_NUM_WORKER"])
assert dist.size() == n, dist.size()
rank = dist.rank()

from jax.experimental import multihost_utils
got = multihost_utils.process_allgather(jnp.array([rank + 10.0]))
np.testing.assert_allclose(np.sort(np.asarray(got).ravel()),
                           [10.0 + i for i in range(n)])

# kvstore reports cluster identity through the same plumbing
kv = mx.kv.create("dist_sync")
assert kv.num_workers == n and kv.rank == rank

# dist_sync value semantics (reference tests/nightly/dist_sync_kvstore.py):
# init broadcasts rank 0's value; push sums across workers exactly
init_val = mx.nd.ones((3, 2)) * (100 + rank)   # ranks disagree on purpose
kv.init("w", init_val)
out = mx.nd.zeros((3, 2))
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), 100.0)   # rank 0 won

kv.push("w", mx.nd.ones((3, 2)) * (rank + 1))      # sum 1..n
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), n * (n + 1) / 2.0)
print("WORKER_OK", rank)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(tmp_path, worker_src, marker, extra_env=None,
                 timeout=180, n=2):
    """Spawn n cluster workers, collect output with a kill-on-timeout
    guard, assert rc=0 + per-rank marker lines."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(worker_src)

    procs = []
    for wid in range(n):
        env = dict(os.environ)
        env.update({
            "REPO": repo,
            "PYTHONPATH": repo,          # drop the axon plugin site
            "JAX_PLATFORMS": "cpu",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(n),
            "DMLC_WORKER_ID": str(wid),
            "DMLC_ROLE": "worker",
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("worker cluster timed out:\n%s" % "\n".join(outs))
    for wid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "worker %d failed:\n%s" % (wid, out)
        assert "%s %d" % (marker, wid) in out, out



@pytest.mark.slow
def test_two_process_cluster(tmp_path):
    _run_workers(tmp_path, _WORKER_SRC, "WORKER_OK")


@pytest.mark.slow
def test_four_process_cluster(tmp_path):
    """Same dist_sync contract over a 4-worker cluster — the DCN path
    beyond pairwise (allgather ordering, 4-way push reduction)."""
    _run_workers(tmp_path, _WORKER_SRC, "WORKER_OK", n=4)


def test_launch_py_local_mode(tmp_path):
    """tools/launch.py local mode (dmlc_tracker 'local' analogue): forks
    N workers with the DMLC_* env and they form one jax.distributed
    cluster."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os, sys\n"
        "sys.path.insert(0, %r)\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from mxnet_tpu.parallel import dist\n"
        "dist.init()\n"
        "assert dist.size() == 2\n"
        "print('LAUNCHED-OK', dist.rank())\n" % repo)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DMLC_PS_ROOT_URI", None)
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(worker)],
        capture_output=True, text=True, timeout=240, env=env)
    assert out.returncode == 0, out.stderr[-800:]
    assert out.stdout.count("LAUNCHED-OK") == 2, out.stdout


_SPMD_WORKER_SRC = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.parallel import dist, make_mesh, make_train_step
import jax

dist.init()
assert jax.process_count() == 2
# 2 processes x 4 local virtual devices = one 8-device global data mesh
devices = jax.devices()
assert len(devices) == 8, devices

def mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    return mx.sym.SoftmaxOutput(net, name="softmax")

rng = np.random.default_rng(0)          # same data on every process
X = rng.standard_normal((32, 8)).astype(np.float32)
y = (X @ rng.standard_normal(8) > 0).astype(np.float32)

def run(mesh):
    step = make_train_step(mlp(), optimizer="sgd",
                           optimizer_params={"rescale_grad": 1.0 / 32},
                           mesh=mesh)
    mx.random.seed(3); np.random.seed(3)
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    batch = step.place_batch({"data": X, "softmax_label": y})
    k = jax.random.PRNGKey(0)
    for _ in range(4):
        state, outs = step(state, batch, 0.2, k)
    # gather replicated params to host
    return {n: np.asarray(jax.device_get(v))
            for n, v in state[0].items()}

# the REAL multi-host step: batch + grads span both processes, the
# grad all-reduce rides the cross-process transport
multi = run(make_mesh({"data": 8}, devices=devices))
# reference: same data, same seeds, single process worth of devices
single = run(make_mesh({"data": 4}, devices=jax.local_devices()))
for n in multi:
    np.testing.assert_allclose(multi[n], single[n], rtol=2e-5,
                               atol=1e-6, err_msg=n)
print("SPMD_WORKER_OK", dist.rank())
"""


@pytest.mark.slow
def test_two_process_spmd_train_step(tmp_path):
    """The full compiled train step over a GLOBAL mesh spanning two
    processes: fwd+bwd+update with the grad all-reduce crossing the
    process boundary, numerically identical to a local-mesh run — the
    DCN-path depth check on the SURVEY §4 multi-process pattern."""
    _run_workers(
        tmp_path, _SPMD_WORKER_SRC, "SPMD_WORKER_OK",
        extra_env={"XLA_FLAGS":
                   "--xla_force_host_platform_device_count=4"},
        timeout=300)
