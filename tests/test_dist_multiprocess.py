"""Multi-process-on-one-host distributed test — the SURVEY §4 pattern
(reference tests/nightly/dist_sync_kvstore.py launched with the `local`
dmlc_tracker): two local processes form a cluster via the DMLC_* env
shim (parallel/dist.py) and run a real cross-process collective.
"""
import os
import socket
import subprocess
import sys

import pytest

_WORKER_SRC = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.parallel import dist
import jax, jax.numpy as jnp

dist.init()
assert dist.size() == 2, dist.size()
rank = dist.rank()

from jax.experimental import multihost_utils
got = multihost_utils.process_allgather(jnp.array([rank + 10.0]))
np.testing.assert_allclose(np.sort(np.asarray(got).ravel()),
                           [10.0, 11.0])

# kvstore reports cluster identity through the same plumbing
kv = mx.kv.create("dist_sync")
assert kv.num_workers == 2 and kv.rank == rank

# dist_sync value semantics (reference tests/nightly/dist_sync_kvstore.py):
# init broadcasts rank 0's value; push sums across workers exactly
init_val = mx.nd.ones((3, 2)) * (100 + rank)   # ranks disagree on purpose
kv.init("w", init_val)
out = mx.nd.zeros((3, 2))
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), 100.0)   # rank 0 won

kv.push("w", mx.nd.ones((3, 2)) * (rank + 1))      # 1 + 2 across workers
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), 3.0)
print("WORKER_OK", rank)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_cluster(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(_WORKER_SRC)

    procs = []
    for wid in range(2):
        env = dict(os.environ)
        env.update({
            "REPO": repo,
            "PYTHONPATH": repo,          # drop the axon plugin site
            "JAX_PLATFORMS": "cpu",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": "2",
            "DMLC_WORKER_ID": str(wid),
            "DMLC_ROLE": "worker",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))

    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("cluster formation timed out:\n%s"
                    % "\n".join(outs))
    for wid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "worker %d failed:\n%s" % (wid, out)
        assert "WORKER_OK %d" % wid in out


def test_launch_py_local_mode(tmp_path):
    """tools/launch.py local mode (dmlc_tracker 'local' analogue): forks
    N workers with the DMLC_* env and they form one jax.distributed
    cluster."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os, sys\n"
        "sys.path.insert(0, %r)\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from mxnet_tpu.parallel import dist\n"
        "dist.init()\n"
        "assert dist.size() == 2\n"
        "print('LAUNCHED-OK', dist.rank())\n" % repo)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DMLC_PS_ROOT_URI", None)
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(worker)],
        capture_output=True, text=True, timeout=240, env=env)
    assert out.returncode == 0, out.stderr[-800:]
    assert out.stdout.count("LAUNCHED-OK") == 2, out.stdout
