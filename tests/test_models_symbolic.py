"""Symbolic model catalog (models/) — each must build, infer shapes, and
run one forward+backward step (reference analogue:
example/image-classification/symbols/*)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.models import (alexnet, googlenet, inception_bn,
                              inception_resnet_v2, inception_v3,
                              inception_v4, mobilenet, resnet, resnext,
                              vgg)

CASES = [
    ("alexnet", lambda: alexnet.get_symbol(10), (2, 3, 224, 224)),
    ("vgg11", lambda: vgg.get_symbol(10, num_layers=11), (2, 3, 64, 64)),
    ("vgg16bn", lambda: vgg.get_symbol(10, num_layers=16,
                                       batch_norm=True), (2, 3, 32, 32)),
    ("mobilenet", lambda: mobilenet.get_symbol(10, multiplier=0.25),
     (2, 3, 64, 64)),
    ("resnext50", lambda: resnext.get_symbol(10, num_layers=50,
                                             cardinality=4,
                                             bottleneck_width=4),
     (2, 3, 64, 64)),
    ("inception_bn", lambda: inception_bn.get_symbol(10),
     (2, 3, 128, 128)),
    # 139px keeps the CPU test fast; global pooling absorbs the grid size
    ("inception_v3", lambda: inception_v3.get_symbol(10),
     (2, 3, 139, 139)),
    ("googlenet", lambda: googlenet.get_symbol(10), (2, 3, 224, 224)),
    ("inception_v4", lambda: inception_v4.get_symbol(10),
     (2, 3, 139, 139)),
    ("inception_resnet_v2",
     lambda: inception_resnet_v2.get_symbol(10), (2, 3, 139, 139)),
    ("resnet18_v1", lambda: resnet.get_symbol(
        10, num_layers=18, image_shape=(3, 64, 64), version=1),
     (2, 3, 64, 64)),
]


# the heaviest variants ride the "large sweeps" tier — the 870 s
# tier-1 wall-clock budget forces the cut, and the fast tier keeps one
# representative per family: googlenet (inception/concat blocks),
# vgg11 (plain conv stacks), mobilenet (depthwise), resnet18_v1
# (residual). Every case still builds + runs when the slow tier does.
_SLOW_CASES = {"inception_v4", "inception_resnet_v2", "inception_v3",
               # 9-47 s each on the 1-core tier-1 host
               "inception_bn", "alexnet", "resnext50", "vgg16bn"}


@pytest.mark.parametrize(
    "name,build,shape",
    [pytest.param(*c, id=c[0],
                  marks=(pytest.mark.slow,) if c[0] in _SLOW_CASES
                  else ()) for c in CASES])
def test_model_forward_backward(name, build, shape):
    net = build()
    arg_shapes, out_shapes, _ = net.infer_shape(
        data=shape, softmax_label=(shape[0],))
    assert out_shapes[0] == (shape[0], 10)
    ex = net.simple_bind(mx.cpu(), data=shape,
                         softmax_label=(shape[0],),
                         grad_req="write")
    rng = np.random.RandomState(0)
    for k, v in ex.arg_dict.items():
        if k == "data":
            v[:] = rng.randn(*v.shape).astype(np.float32) * 0.1
        elif k == "softmax_label":
            v[:] = rng.randint(0, 10, v.shape).astype(np.float32)
        elif v.ndim >= 1:
            v[:] = rng.randn(*v.shape).astype(np.float32) * 0.05
    out = ex.forward(is_train=True)[0].asnumpy()
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-3)
    ex.backward()
    g = ex.grad_dict[[k for k in ex.grad_dict if "weight" in k][0]]
    assert np.isfinite(g.asnumpy()).all()


def test_get_symbol_factory():
    """models.get_symbol(name) mirrors the reference's --network flag."""
    from mxnet_tpu import models
    net = models.get_symbol("vgg", num_classes=7, num_layers=11)
    assert net.infer_shape(data=(1, 3, 32, 32),
                           softmax_label=(1,))[1][0] == (1, 7)
    with pytest.raises(ValueError):
        models.get_symbol("not-a-network")
