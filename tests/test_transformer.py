"""Transformer LM model-family tests: trains through TrainStep (SPMD)
and Module, uses the flash-attention op, exports through the
predictor."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.models import transformer
from mxnet_tpu.parallel import make_mesh, make_train_step


def _corpus(n, T, vocab, seed=0):
    """Deterministic next-token task: t_{i+1} = (t_i + 3) % vocab."""
    rng = np.random.RandomState(seed)
    starts = rng.randint(0, vocab, n)
    toks = (starts[:, None] + 3 * np.arange(T)[None, :]) % vocab
    labels = np.roll(toks, -1, axis=1).astype(np.float32)
    labels[:, -1] = -1
    return toks.astype(np.float32), labels


def test_trainstep_convergence():
    vocab, T, B = 16, 12, 16
    sym = transformer.get_symbol(vocab, T, num_layers=2, num_heads=2,
                                 dim=32)
    step = make_train_step(sym, optimizer="adam",
                           optimizer_params={"learning_rate": 3e-3})
    state = step.init_state(mx.init.Xavier(), {"data": (B, T),
                                               "softmax_label": (B, T)})
    toks, labels = _corpus(B, T, vocab)
    bv = step.place_batch({"data": toks, "softmax_label": labels})
    rng = jax.random.PRNGKey(0)

    from tests._lm_utils import lm_nll
    state, outs = step(state, bv, 3e-3, rng)
    first = lm_nll(outs, labels, vocab)
    for _ in range(60):
        state, outs = step(state, bv, 3e-3, rng)
    last = lm_nll(outs, labels, vocab)
    assert last < first * 0.2, (first, last)


def test_module_training():
    vocab, T, B = 12, 8, 8
    sym = transformer.get_symbol(vocab, T, num_layers=1, num_heads=2,
                                 dim=16)
    toks, labels = _corpus(64, T, vocab, seed=1)
    it = mx.io.NDArrayIter(toks, labels, batch_size=B,
                           label_name="softmax_label")
    mod = mx.mod.Module(sym, ("data",), ("softmax_label",))
    mod.fit(it, num_epoch=8, optimizer="adam",
            optimizer_params={"learning_rate": 3e-3},
            eval_metric=mx.metric.Perplexity(-1))
    it.reset()
    score = mod.score(it, mx.metric.Perplexity(-1))[0][1]
    assert score < 4.0, score


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device mesh")
def test_trainstep_on_mesh_with_tp():
    vocab, T, B = 16, 8, 16
    mesh = make_mesh({"data": 4, "model": 2},
                     devices=jax.devices()[:8])
    sym = transformer.get_symbol(vocab, T, num_layers=1, num_heads=2,
                                 dim=32)
    step = make_train_step(sym, optimizer="adam", mesh=mesh,
                           compute_dtype="bfloat16")
    state = step.init_state(mx.init.Xavier(), {"data": (B, T),
                                               "softmax_label": (B, T)})
    toks, labels = _corpus(B, T, vocab, seed=2)
    bv = step.place_batch({"data": toks, "softmax_label": labels})
    state, outs = step(state, bv, 1e-3, jax.random.PRNGKey(0))
    out = np.asarray(jax.device_get(outs[0]))
    assert out.shape == (B * T, vocab)
    assert np.isfinite(out).all()
    # master weights stay f32 under bf16 compute
    assert all(v.dtype == np.float32 for v in state[0].values())


def test_bucketing_shares_pos_table():
    """Buckets of different seq_len share one (max_len, dim) position
    table (each slices its prefix)."""
    vocab, B = 12, 8
    buckets = [6, 10]

    def sym_gen(T):
        s = transformer.get_symbol(vocab, T, num_layers=1, num_heads=2,
                                   dim=16, max_len=max(buckets))
        return s, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10)
    mod.bind([mx.io.DataDesc("data", (B, 10))],
             [mx.io.DataDesc("softmax_label", (B, 10))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 1e-3})
    for T in (10, 6, 10, 6):
        toks, labels = _corpus(B, T, vocab, seed=T)
        batch = mx.io.DataBatch(
            data=[mx.nd.array(toks)], label=[mx.nd.array(labels)],
            bucket_key=T,
            provide_data=[mx.io.DataDesc("data", (B, T))],
            provide_label=[mx.io.DataDesc("softmax_label", (B, T))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    params = mod.get_params()[0]
    assert params["pos_embed_weight"].shape == (10, 16)


def test_predictor_export(tmp_path):
    vocab, T = 12, 8
    sym = transformer.get_symbol(vocab, T, num_layers=1, num_heads=2,
                                 dim=16)
    step = make_train_step(sym, optimizer="adam")
    state = step.init_state(mx.init.Xavier(), {"data": (2, T),
                                               "softmax_label": (2, T)})
    params = {k: np.asarray(v) for k, v in state[0].items()}
    # the label routes through a reshape before the loss head, so its
    # shape is not inferable from data alone — declare it as an input
    # and feed dummies (SoftmaxOutput ignores labels at inference)
    pred = mx.Predictor(sym, params,
                        data_names=("data", "softmax_label"))
    toks = np.zeros((2, T), np.float32)
    dummy = np.zeros((2, T), np.float32)
    out = pred.forward(data=toks, softmax_label=dummy)[0]
    assert out.shape == (2 * T, vocab)

    art = pred.export(str(tmp_path / "lm"),
                      {"data": (2, T), "softmax_label": (2, T)})
    loaded = mx.predictor.CompiledPredictor.load(str(tmp_path / "lm"))
    got = loaded.forward(data=toks, softmax_label=dummy)[0].asnumpy()
    np.testing.assert_allclose(got, out.asnumpy(), rtol=1e-5, atol=1e-6)


def test_moe_transformer_trains():
    """num_experts swaps FFNs for _contrib_MoEFFN; the LM must still
    train end-to-end through Module with decreasing loss."""
    from mxnet_tpu.models import transformer
    rng = np.random.RandomState(0)
    V, T, B = 20, 8, 16
    sym_net = transformer.get_symbol(V, T, num_layers=1, num_heads=2,
                                     dim=32, num_experts=4)
    args = sym_net.list_arguments()
    assert "layer0_gate_weight" in args
    assert "layer0_experts_w1_weight" in args

    seq = rng.randint(0, V, (64, T + 1))
    X = seq[:, :-1].astype(np.float32)
    Y = seq[:, 1:].astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=B, shuffle=True)
    mod = mx.mod.Module(sym_net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3})
    metric = mx.metric.Perplexity(ignore_label=-1)
    ppl = []
    for epoch in range(8):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        ppl.append(metric.get()[1])
    assert ppl[-1] < ppl[0] * 0.8, ppl


def test_chunked_loss_head_matches_dense():
    """loss_chunk replaces FullyConnected+SoftmaxOutput with the fused
    chunked-CE head (`_contrib_ChunkedSoftmaxCE`) whose live memory is
    (chunk, V) instead of (B*T, V) — the 64k-token single-chip
    enabler. Parameter gradients must be EXACTLY SoftmaxOutput's
    (same scaling, same ignore handling), proven by running one
    train step from identical inits under both heads, with a chunk
    that does NOT divide B*T (pad rows must contribute nothing)."""
    V, T, B = 50, 12, 3
    rng = np.random.RandomState(0)
    batch = {"data": rng.randint(0, V, (B, T)).astype(np.float32),
             "softmax_label":
                 rng.randint(-1, V, (B, T)).astype(np.float32)}
    results = {}
    for tag, kw in (("dense", {}), ("chunk", {"loss_chunk": 7})):
        mx.random.seed(3)
        sym = transformer.get_symbol(V, T, num_layers=1, num_heads=2,
                                     dim=16, **kw)
        st = make_train_step(sym, optimizer="sgd", donate=False)
        state = st.init_state(mx.init.Xavier(),
                              {"data": (B, T),
                               "softmax_label": (B, T)})
        new_state, outs = st(state, st.place_batch(batch), 0.1,
                             jax.random.PRNGKey(0))
        results[tag] = (
            {k: np.asarray(jax.device_get(v))
             for k, v in new_state[0].items()},
            np.asarray(jax.device_get(outs[0])))
    dense_p, _ = results["dense"]
    chunk_p, loss = results["chunk"]
    assert loss.shape == (B, T)
    assert np.isfinite(loss).all()
    # ignored positions carry exactly zero loss
    ignored = batch["softmax_label"] == -1
    assert np.abs(loss[ignored]).max() == 0.0
    for k in dense_p:
        np.testing.assert_allclose(
            dense_p[k], chunk_p[k], rtol=2e-5, atol=2e-5,
            err_msg="param %s diverged between heads" % k)


def test_chunked_loss_op_values():
    """Op-level: per-token values equal the explicit log-softmax NLL
    with SoftmaxOutput's valid-normalization scaling."""
    from mxnet_tpu.ops.registry import get_op
    rng = np.random.RandomState(1)
    N, D, V = 11, 8, 13
    x = jnp.asarray(rng.randn(N, D), jnp.float32)
    w = jnp.asarray(rng.randn(V, D), jnp.float32)
    b = jnp.asarray(rng.randn(V), jnp.float32)
    lab = rng.randint(-1, V, N).astype(np.float32)
    out = get_op("_contrib_ChunkedSoftmaxCE").fn(
        x, w, b, jnp.asarray(lab), chunk=4, use_ignore=True,
        ignore_label=-1.0, normalization="valid")
    logits = np.asarray(x) @ np.asarray(w).T + np.asarray(b)
    lse = np.log(np.exp(logits - logits.max(-1, keepdims=True))
                 .sum(-1)) + logits.max(-1)
    valid = lab >= 0
    want = np.zeros(N)
    want[valid] = (lse[valid]
                   - logits[valid, lab[valid].astype(int)]) \
        / max(valid.sum(), 1)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5,
                               atol=1e-5)


@pytest.mark.skipif(jax.device_count() < 8,
                    reason="needs 8-device mesh")
def test_chunked_loss_head_on_mesh():
    """The chunked-CE head must lower under GSPMD (its (B*T, D)
    reshape + checkpointed chunk scan) and produce the same losses as
    the single-device chunked run — dp x tp mesh, float32 for exact
    comparison."""
    V, T, B = 64, 16, 8
    rng = np.random.RandomState(0)
    batch = {"data": rng.randint(0, V, (B, T)).astype(np.float32),
             "softmax_label":
                 rng.randint(-1, V, (B, T)).astype(np.float32)}
    losses = {}
    for tag, mesh in (("mesh", make_mesh({"data": 4, "model": 2},
                                         devices=jax.devices()[:8])),
                      ("single", None)):
        mx.random.seed(5)
        sym = transformer.get_symbol(V, T, num_layers=1, num_heads=2,
                                     dim=16, loss_chunk=8)
        st = make_train_step(sym, optimizer="sgd", mesh=mesh,
                             donate=False)
        state = st.init_state(mx.init.Xavier(),
                              {"data": (B, T),
                               "softmax_label": (B, T)})
        _, outs = st(state, st.place_batch(batch), 0.1,
                     jax.random.PRNGKey(0))
        losses[tag] = np.asarray(jax.device_get(outs[0]))
    assert losses["mesh"].shape == (B, T)
    np.testing.assert_allclose(losses["mesh"], losses["single"],
                               rtol=1e-5, atol=1e-6)


def test_segsum_embedding_grad_matches_scatter(monkeypatch):
    """MXNET_EMBED_GRAD=segsum (sort + segment-sum embedding backward,
    the staged experiment for the traced scatter-update headroom):
    bit-equal gradients to autodiff's scatter-add in f32 (duplicate
    ids included), allclose in bf16 (segsum accumulates duplicates in
    f32 where scatter rounds per step — strictly less rounding), and
    alive on an EMPTY batch (reshape(-1) cannot infer there)."""
    from mxnet_tpu.ops.indexing import _embedding
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 7, (3, 5)), jnp.float32)
    w = jnp.asarray(rng.randn(7, 4), jnp.float32)
    dy = jnp.asarray(rng.randn(3, 5, 4), jnp.float32)

    def grad(w_, dy_):
        return np.asarray(jax.grad(
            lambda p: jnp.sum((_embedding(ids, p) *
                               dy_).astype(jnp.float32)))(w_))

    monkeypatch.delenv("MXNET_EMBED_GRAD", raising=False)
    g_scatter = grad(w, dy)
    g_scatter_bf = grad(w.astype(jnp.bfloat16), dy.astype(jnp.bfloat16))
    monkeypatch.setenv("MXNET_EMBED_GRAD", "segsum")
    g_segsum = grad(w, dy)
    g_segsum_bf = grad(w.astype(jnp.bfloat16), dy.astype(jnp.bfloat16))
    np.testing.assert_array_equal(g_scatter, g_segsum)
    np.testing.assert_allclose(g_scatter_bf, g_segsum_bf,
                               rtol=2e-2, atol=2e-2)

    empty = jnp.zeros((2, 0), jnp.float32)
    g_empty = np.asarray(jax.grad(lambda p: jnp.sum(
        _embedding(empty, p).astype(jnp.float32)))(w))
    assert g_empty.shape == w.shape and (g_empty == 0).all()


@pytest.mark.slow
def test_chunked_loss_head_bf16_remat():
    """The production long-context configuration: chunked-CE head
    under bf16 compute AND remat (checkpointed chunk scan nested in
    the checkpointed forward) — the exact shape of the live 32k/48k
    runs. Must train with finite, dense-head-close losses. Slow tier
    (~12 s on the 1-core tier-1 host); the chunked head keeps fast
    coverage in test_chunked_loss_head_matches_dense/_on_mesh and the
    op-value test."""
    V, T, B = 50, 12, 4
    rng = np.random.RandomState(0)
    batch = {"data": rng.randint(0, V, (B, T)).astype(np.float32),
             "softmax_label":
                 rng.randint(0, V, (B, T)).astype(np.float32)}
    losses = {}
    for tag, kw in (("dense", {}), ("chunk", {"loss_chunk": 8})):
        mx.random.seed(9)
        sym = transformer.get_symbol(V, T, num_layers=1, num_heads=2,
                                     dim=16, **kw)
        st = make_train_step(sym, optimizer="adam", donate=False,
                             compute_dtype="bfloat16", remat=True)
        state = st.init_state(mx.init.Xavier(),
                              {"data": (B, T),
                               "softmax_label": (B, T)})
        vals = []
        for i in range(3):
            state, outs = st(state, st.place_batch(batch), 1e-3,
                             jax.random.PRNGKey(0))
            if tag == "chunk":
                o = np.asarray(jax.device_get(outs[0])
                               ).astype(np.float32)
                vals.append(float(o.mean()))
            else:                          # dense: probs -> mean NLL
                from tests._lm_utils import lm_nll
                vals.append(lm_nll(
                    [np.asarray(jax.device_get(outs[0]))],
                    batch["softmax_label"], V))
        losses[tag] = vals
        assert all(np.isfinite(v) for v in vals), (tag, vals)
    # both heads train downhill from identical inits in bf16
    assert losses["chunk"][-1] < losses["chunk"][0]
    assert losses["dense"][-1] < losses["dense"][0]
