"""CTC loss vs a from-scratch numpy dynamic program (reference
src/operator/contrib/ctc_loss.cc semantics)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def np_ctc_loss(logits_tbc, labels, blank=0):
    """Negative log likelihood of `labels` under CTC for ONE example.
    logits_tbc: (T, C) unnormalized; labels: list of ints (no blanks)."""
    T, C = logits_tbc.shape
    e = np.exp(logits_tbc - logits_tbc.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    ext = [blank]
    for l in labels:
        ext += [l, blank]
    S = len(ext)
    alpha = np.zeros((T, S))
    alpha[0, 0] = probs[0, ext[0]]
    if S > 1:
        alpha[0, 1] = probs[0, ext[1]]
    for t in range(1, T):
        for s in range(S):
            a = alpha[t - 1, s]
            if s >= 1:
                a += alpha[t - 1, s - 1]
            if s >= 2 and ext[s] != blank and ext[s] != ext[s - 2]:
                a += alpha[t - 1, s - 2]
            alpha[t, s] = a * probs[t, ext[s]]
    p = alpha[T - 1, S - 1] + (alpha[T - 1, S - 2] if S > 1 else 0.0)
    return -np.log(max(p, 1e-30))


def test_ctc_loss_matches_numpy_blank_first():
    rng = np.random.RandomState(0)
    T, B, C = 6, 2, 5
    data = rng.randn(T, B, C).astype(np.float32)
    # blank_label="first": labels use 1..C-1, padding 0
    label = np.array([[1, 3, 2], [4, 1, 0]], np.float32)
    out = nd.CTCLoss(nd.array(data), nd.array(label)).asnumpy()
    want = [np_ctc_loss(data[:, 0], [1, 3, 2], blank=0),
            np_ctc_loss(data[:, 1], [4, 1], blank=0)]
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_ctc_loss_blank_last():
    rng = np.random.RandomState(1)
    T, B, C = 5, 1, 4
    data = rng.randn(T, B, C).astype(np.float32)
    label = np.array([[0, 2, -1]], np.float32)  # padding -1, blank C-1
    out = nd.CTCLoss(nd.array(data), nd.array(label),
                     blank_label="last").asnumpy()
    want = [np_ctc_loss(data[:, 0], [0, 2], blank=C - 1)]
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_ctc_loss_explicit_lengths():
    rng = np.random.RandomState(2)
    T, B, C = 7, 2, 5
    data = rng.randn(T, B, C).astype(np.float32)
    label = np.array([[1, 3, 2], [4, 1, 2]], np.float32)
    out = nd.CTCLoss(nd.array(data), nd.array(label),
                     use_data_lengths=True, use_label_lengths=True,
                     data_lengths=nd.array(np.array([5, 7], np.float32)),
                     label_lengths=nd.array(np.array([2, 3], np.float32))
                     ).asnumpy()
    want = [np_ctc_loss(data[:5, 0], [1, 3], blank=0),
            np_ctc_loss(data[:, 1], [4, 1, 2], blank=0)]
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_ctc_label_lengths_only():
    """use_label_lengths without data lengths (the gluon kwarg path that
    was dead in round 1)."""
    rng = np.random.RandomState(3)
    data = rng.randn(6, 1, 5).astype(np.float32)
    label = np.array([[2, 1, 3]], np.float32)
    out = nd.CTCLoss(nd.array(data), nd.array(label),
                     use_label_lengths=True,
                     label_lengths=nd.array(np.array([2], np.float32))
                     ).asnumpy()
    want = [np_ctc_loss(data[:, 0], [2, 1], blank=0)]
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_gluon_ctc_loss():
    """gluon.loss.CTCLoss was DOA in round 1 (no CTCLoss op registered)."""
    from mxnet_tpu.gluon.loss import CTCLoss

    loss = CTCLoss()
    rng = np.random.RandomState(4)
    pred = nd.array(rng.randn(2, 6, 5).astype(np.float32))   # NTC
    label = nd.array(np.array([[1, 3, 2], [4, 1, 0]], np.float32))
    out = loss(pred, label).asnumpy()
    assert out.shape == (2,)
    assert np.all(np.isfinite(out)) and np.all(out > 0)


def test_ctc_loss_gradient_descends():
    """Gradient flows: a few SGD steps reduce the loss."""
    from mxnet_tpu import autograd

    rng = np.random.RandomState(5)
    x = nd.array(rng.randn(6, 1, 4).astype(np.float32))
    label = nd.array(np.array([[1, 2]], np.float32))
    x.attach_grad()
    losses = []
    for _ in range(30):
        with autograd.record():
            l = nd.CTCLoss(x, label)
        l.backward()
        x._set_data(x._data - 0.5 * x.grad._data)
        losses.append(float(l.asnumpy()[0]))
    assert losses[-1] < losses[0] * 0.6, losses[::10]
