"""Regenerate the tiny pretrained-model fixture.

Run from the repo root:
    PYTHONPATH=. JAX_PLATFORMS=cpu python tests/fixtures/make_zoo_fixture.py

Produces (committed to git; ~300KB total):
    zoo_resnet8-symbol.json / zoo_resnet8-0000.params   checkpoint files
    zoo_resnet8_golden.npz                              input + logits

A seeded, briefly-trained CIFAR-style ResNet-8 stands in for a
published zoo checkpoint (no network in CI): what the test guards is
that load_checkpoint -> Predictor and the exported CompiledPredictor
both reproduce the recorded logits bit-for-tolerance, the reference's
pretrained inference contract (tests/python/gpu/test_forward.py).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

import mxnet_tpu as mx
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.models import resnet
from mxnet_tpu.parallel import make_train_step

HERE = os.path.dirname(os.path.abspath(__file__))
PREFIX = os.path.join(HERE, "zoo_resnet8")


def main():
    sym = resnet.get_symbol(num_classes=10, num_layers=8,
                            image_shape=(3, 16, 16))
    step = make_train_step(sym, optimizer="sgd",
                           optimizer_params={"momentum": 0.9,
                                             "rescale_grad": 1.0 / 64})
    mx.random.seed(1234)
    np.random.seed(1234)
    state = step.init_state(Xavier(), {"data": (64, 3, 16, 16),
                                       "softmax_label": (64,)})
    rng_np = np.random.RandomState(99)
    X = rng_np.randn(64, 3, 16, 16).astype(np.float32)
    y = rng_np.randint(0, 10, 64).astype(np.float32)
    batch = step.place_batch({"data": X, "softmax_label": y})
    rng = jax.random.PRNGKey(0)
    for _ in range(10):   # a few steps so BN stats/params are non-trivial
        state, _ = step(state, batch, 0.05, rng)
    params, _opt, aux = state

    from mxnet_tpu import nd
    arg_params = {k: nd.array(np.asarray(v)) for k, v in params.items()}
    aux_params = {k: nd.array(np.asarray(v)) for k, v in aux.items()}
    mx.model.save_checkpoint(PREFIX, 0, sym, arg_params, aux_params)

    probe = rng_np.randn(2, 3, 16, 16).astype(np.float32)
    pred = mx.predictor.load_checkpoint_predictor(PREFIX, 0)
    logits = pred.forward(probe)[0].asnumpy()
    np.savez(PREFIX + "_golden.npz", probe=probe, logits=logits)
    print("fixture written:", PREFIX, "logits[0,:4] =", logits[0, :4])


if __name__ == "__main__":
    main()
