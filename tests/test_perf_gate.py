"""The journal-backed perf-regression gate (ISSUE 12,
docs/perf_gates.md): fingerprint extraction, the --bless round trip,
and — the load-bearing part — that each class of injected regression
(an extra per-step host sync, a steady-state recompile, a missing
trace span, a vanished counter) FAILS the gate with a diagnostic
naming the PR-won property it protects, while seeded ±25% time jitter
does NOT flap the noise-tolerant time bounds."""
import copy
import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

pytestmark = pytest.mark.gate


def _load_perf_gate():
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(REPO, "tools", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)
    return pg


@pytest.fixture(scope="module")
def pg():
    return _load_perf_gate()


# ---------------------------------------------------------------------------
# synthetic journal/trace for the pure-function tests (no subprocess)
# ---------------------------------------------------------------------------

def _synthetic_records():
    journal = [
        {"v": 1, "kind": "run_start", "schema": 1},
        {"v": 1, "kind": "event", "event": "fit.start"},
        {"v": 1, "kind": "event", "event": "compile",
         "fields": {"wall_ms": 100.0}},
        {"v": 1, "kind": "step", "step": 0, "wall_ms": 120.0,
         "samples": 24, "compile": True},
        {"v": 1, "kind": "step", "step": 1, "wall_ms": 10.0,
         "samples": 24},
        {"v": 1, "kind": "step", "step": 2, "wall_ms": 12.0,
         "samples": 24},
        {"v": 1, "kind": "event", "event": "gate.probe",
         "fields": {"max_step_syncs_steady": 1, "elapsed_ms": 150.0}},
        {"v": 1, "kind": "snapshot", "metrics": {
            "host_syncs": {"type": "counter", "value": 4},
            "ps.retries": {"type": "counter", "value": 2},
            "trainstep.jit_cache_size": {"type": "gauge", "value": 1.0},
            "trainstep.step_ms": {"type": "histogram", "count": 3},
        }},
    ]
    trace = [
        {"v": 1, "kind": "trace_start", "schema": 1},
        {"v": 1, "kind": "span", "name": "train.step", "span": "9.1",
         "parent": None, "trace": "9.0"},
        {"v": 1, "kind": "span", "name": "step.window_wait",
         "span": "9.2", "parent": "9.1", "trace": "9.0"},
        {"v": 1, "kind": "instant", "name": "guardrail.masked_step",
         "parent": "9.1", "trace": "9.0"},
    ]
    return journal, trace


def _fingerprint(pg, scenario="trainstep"):
    journal, trace = _synthetic_records()
    return pg.extract_fingerprint(scenario, journal, trace)


def _baseline(pg, fp):
    return {"scenario": fp["scenario"], "time_ratio": 3.0,
            "fingerprint": copy.deepcopy(fp)}


# ---------------------------------------------------------------------------
# fingerprint extraction round trip
# ---------------------------------------------------------------------------

def test_fingerprint_extraction_and_self_compare(pg):
    fp = _fingerprint(pg)
    assert fp["counts"]["journal_schema"] == 1
    assert fp["counts"]["steps"] == 3
    assert fp["counts"]["compile_events"] == 1
    assert fp["counts"]["compile_steps"] == [0]
    assert fp["counts"]["counters"]["ps.retries"] == 2
    # gauge values normalize to int so baselines read cleanly
    assert fp["counts"]["gauges"]["trainstep.jit_cache_size"] == 1
    assert fp["counts"]["probe"]["max_step_syncs_steady"] == 1
    # probe *_ms fields route to the ratio-compared times, not counts
    assert fp["times"]["elapsed_ms"] == 150.0
    assert "elapsed_ms" not in fp["counts"]["probe"]
    # steady-state p50 excludes the compile-flagged step (nearest-rank
    # with banker's rounding: index round(0.5) == 0 -> 10.0, the
    # telemetry_report._quantile convention)
    assert fp["times"]["step_ms_p50"] == 10.0
    assert fp["trace"]["spans"] == ["step.window_wait", "train.step"]
    assert fp["trace"]["edges"] == [
        "train.step>guardrail.masked_step",
        "train.step>step.window_wait"]
    assert pg.compare(_baseline(pg, fp), fp) == []
    # json round trip is identity (committed baselines are json)
    again = json.loads(json.dumps(fp))
    assert pg.compare(_baseline(pg, fp), again) == []


def test_fingerprint_deterministic_ordering(pg):
    """Two extractions over the same records serialize identically —
    the run-twice determinism contract, minus the subprocess."""
    a = json.dumps(_fingerprint(pg), sort_keys=True)
    b = json.dumps(_fingerprint(pg), sort_keys=True)
    assert a == b


# ---------------------------------------------------------------------------
# injected regressions are caught, with the right diagnostic
# ---------------------------------------------------------------------------

def _fails_for(pg, mutate, **kw):
    fp = _fingerprint(pg)
    base = _baseline(pg, fp)
    live = copy.deepcopy(fp)
    mutate(live)
    fails = pg.compare(base, live, **kw)
    assert fails, "mutation was not caught"
    return "\n".join(f.format() for f in fails)


def test_extra_host_sync_names_pr2(pg):
    msg = _fails_for(pg, lambda fp: fp["counts"]["probe"].update(
        max_step_syncs_steady=2))
    assert "max_step_syncs_steady" in msg
    assert "ONE blocking host sync" in msg


def test_recompile_names_pr11(pg):
    msg = _fails_for(pg, lambda fp: fp["counts"]["gauges"].update(
        {"trainstep.jit_cache_size": 2}))
    assert "step-2-recompile" in msg or "recompile" in msg
    assert "donated" in msg

    msg = _fails_for(
        pg, lambda fp: fp["counts"].update(compile_steps=[0, 2]))
    assert "compile" in msg


def test_missing_span_names_pr10(pg):
    def cut(fp):
        fp["trace"]["spans"].remove("step.window_wait")
        fp["trace"]["edges"].remove("train.step>step.window_wait")
    msg = _fails_for(pg, cut)
    assert "trace." in msg and "span vocabulary" in msg


def test_missing_counter_names_pr1(pg):
    def cut(fp):
        del fp["counts"]["counters"]["ps.retries"]
    msg = _fails_for(pg, cut)
    assert "ps.retries" in msg and "missing from live run" in msg
    assert "retry" in msg


def test_schema_bump_is_caught(pg):
    msg = _fails_for(pg, lambda fp: fp["counts"].update(
        journal_schema=2))
    assert "journal_schema" in msg and "SCHEMA_VERSION" in msg


def test_new_untracked_field_asks_for_rebless(pg):
    msg = _fails_for(pg, lambda fp: fp["counts"]["counters"].update(
        {"brand.new_counter": 1}))
    assert "re-bless" in msg


# ---------------------------------------------------------------------------
# time bounds: ±25% seeded jitter never flaps, big regressions fail
# ---------------------------------------------------------------------------

def test_time_jitter_tolerated_but_blowup_fails(pg):
    import random
    fp = _fingerprint(pg)
    base = _baseline(pg, fp)
    rng = random.Random(12345)
    for _ in range(20):                       # seeded ±25% jitter
        live = copy.deepcopy(fp)
        jitter = 1.0 + rng.uniform(-0.25, 0.25)
        live["times"] = {k: v * jitter for k, v in fp["times"].items()}
        assert pg.compare(base, live) == [], \
            "time gate flapped at %.2fx" % jitter
    live = copy.deepcopy(fp)
    live["times"]["step_ms_p50"] = fp["times"]["step_ms_p50"] * 4.0
    fails = pg.compare(base, live)
    assert fails and "times.step_ms_p50" in fails[0].format()
    assert "ratio" in fails[0].format()
    # --no-time escape hatch
    assert pg.compare(base, live, check_times=False) == []
    # env override widens the tolerance
    os.environ["MXNET_GATE_TIME_RATIO"] = "10"
    try:
        assert pg.compare(base, live) == []
    finally:
        del os.environ["MXNET_GATE_TIME_RATIO"]


# ---------------------------------------------------------------------------
# committed baselines stay well-formed
# ---------------------------------------------------------------------------

def test_committed_baselines_parse_and_cover_scenarios(pg):
    bdir = os.path.join(REPO, "perf_baselines")
    files = {f[:-5] for f in os.listdir(bdir) if f.endswith(".json")}
    assert files == set(pg.SCENARIOS), \
        "perf_baselines/ out of sync with SCENARIOS"
    for name in files:
        base = pg.load_baseline(name)
        fp = base["fingerprint"]
        assert fp["gate_schema"] == pg.GATE_SCHEMA
        assert fp["scenario"] == name
        for key in ("counts", "trace", "times"):
            assert key in fp, (name, key)
        assert fp["counts"]["journal_schema"] == 1
        # a baseline must compare clean against itself
        assert pg.compare(base, fp) == []


def test_gate_reports_dead_scenario_cleanly(pg, tmp_path):
    """A scenario child that dies before producing any journal is a
    gate FAILURE with the child's stderr attached — never a traceback
    (the bench_common error-stub contract, applied to the gate). The
    child resolves the scenario name itself, so a name only the parent
    knows makes it die deterministically before opening the journal."""
    fp, err = pg.run_scenario("no_such_scenario_xyz",
                              str(tmp_path / "out"))
    assert fp is None and isinstance(err, str)
    assert "no_such_scenario_xyz" in err and "rc=" in err


# ---------------------------------------------------------------------------
# end-to-end: one real scenario, bless + deterministic re-check
# ---------------------------------------------------------------------------

def test_trainstep_scenario_bless_and_recheck_deterministic(
        pg, tmp_path):
    """Acceptance: run the trainstep scenario twice back-to-back on
    CPU; --bless from run 1, compare run 2 — every count/shape field
    identical (times go through the ratio gate)."""
    fp1, err = pg.run_scenario("trainstep", str(tmp_path / "r1"))
    assert err is None, err
    path = pg.bless("trainstep", fp1, str(tmp_path / "bl"))
    assert os.path.exists(path)
    base = pg.load_baseline("trainstep", str(tmp_path / "bl"))
    assert pg.compare(base, fp1) == []

    fp2, err = pg.run_scenario("trainstep", str(tmp_path / "r2"))
    assert err is None, err
    fails = pg.compare(base, fp2)
    assert fails == [], "\n".join(f.format() for f in fails)
    assert json.dumps(fp1["counts"], sort_keys=True) \
        == json.dumps(fp2["counts"], sort_keys=True)
    assert json.dumps(fp1["trace"], sort_keys=True) \
        == json.dumps(fp2["trace"], sort_keys=True)
    # the scenario exercises the load-bearing probes
    assert fp1["counts"]["probe"]["max_step_syncs_steady"] <= 1
    assert fp1["counts"]["gauges"]["trainstep.jit_cache_size"] == 1
    assert fp1["counts"]["counters"]["guardrail.masked_steps"] == 1


@pytest.mark.slow
def test_full_gate_all_scenarios_bless_then_pass(pg, tmp_path):
    """All six scenarios, blessed then re-checked (times skipped —
    absolute walls belong to the blessing machine)."""
    rc = pg.main(["--bless", "--baselines", str(tmp_path / "bl"),
                  "--keep", str(tmp_path / "runs1")])
    assert rc == 0
    rc = pg.main(["--baselines", str(tmp_path / "bl"), "--no-time",
                  "--keep", str(tmp_path / "runs2")])
    assert rc == 0


# ---------------------------------------------------------------------------
# tooling glue
# ---------------------------------------------------------------------------

def test_smoke_wrappers_route_through_perf_gate_sh(pg):
    """The CI lint's contract, asserted from pytest too: every
    *_smoke.sh actually DELEGATES to tools/perf_gate.sh (an exec
    line, not a mere mention in a comment)."""
    import re
    tools = os.path.join(REPO, "tools")
    wrappers = [f for f in os.listdir(tools) if f.endswith("_smoke.sh")]
    assert len(wrappers) >= 4
    pat = re.compile(r'^\s*exec .*perf_gate\.sh"? --only', re.M)
    for f in wrappers:
        with open(os.path.join(tools, f)) as fh:
            assert pat.search(fh.read()), f


def test_perf_gate_sh_sections_parse():
    out = subprocess.run(["bash", "-n",
                          os.path.join(REPO, "tools", "perf_gate.sh")],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr


def test_telemetry_report_diff(tmp_path):
    """--diff: step-time/throughput deltas, counter deltas and
    event-vocabulary changes between two journals."""
    spec = importlib.util.spec_from_file_location(
        "telemetry_report",
        os.path.join(REPO, "tools", "telemetry_report.py"))
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)

    def write(path, step_ms, counters, events):
        recs = [{"v": 1, "kind": "run_start", "schema": 1}]
        for ev in events:
            recs.append({"v": 1, "kind": "event", "event": ev})
        for i in range(4):
            recs.append({"v": 1, "kind": "step", "step": i,
                         "wall_ms": step_ms, "samples": 32})
        recs.append({"v": 1, "kind": "snapshot", "metrics": {
            k: {"type": "counter", "value": v}
            for k, v in counters.items()}})
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    old, new = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    write(old, 10.0, {"host_syncs": 4, "ps.retries": 1}, ["fit.start"])
    write(new, 20.0, {"host_syncs": 9}, ["fit.start", "serve.shed"])
    diff = tr.diff_summaries(tr.summarize(tr.load(old)),
                             tr.summarize(tr.load(new)))
    assert diff["step_ms"]["p50"]["pct"] == 100.0
    assert diff["counter_deltas"]["host_syncs"] == {"old": 4, "new": 9}
    assert diff["counter_deltas"]["ps.retries"]["new"] is None
    assert diff["events_added"] == ["serve.shed"]
    assert "step_ms.p50" in diff["suspects"]
    text = tr.format_diff(diff, old, new)
    assert "regression suspects" in text and "host_syncs" in text
    # CLI surface
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "telemetry_report.py"),
         "--diff", old, new],
        capture_output=True, text=True)
    assert out.returncode == 0 and "journal diff" in out.stdout
