"""Gluon tests — reference: tests/python/unittest/test_gluon.py (425 LoC),
test_gluon_data.py, test_gluon_model_zoo.py, test_gluon_rnn.py."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def _toy(n=32, d=16, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal(d)
    y = (X @ w > 0).astype(np.float32)
    return nd.array(X), nd.array(y)


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(8, 4))
    p.initialize(init=mx.init.Xavier())
    assert p.data().shape == (8, 4)
    assert p.grad().shape == (8, 4)
    assert p.list_ctx() is not None
    p.zero_grad()
    np.testing.assert_allclose(p.grad().asnumpy(), 0)


def test_parameter_deferred_init():
    dense = nn.Dense(8)
    dense.initialize()
    with pytest.raises(gluon.DeferredInitializationError):
        dense.weight.data()
    out = dense(nd.ones((2, 3)))
    assert dense.weight.shape == (8, 3)
    assert out.shape == (2, 8)


def test_parameter_sharing():
    d1 = nn.Dense(8, in_units=4, prefix="dense_")
    d2 = nn.Dense(8, in_units=4, prefix="dense_", params=d1.params)
    d1.initialize()
    x = nd.ones((2, 4))
    np.testing.assert_allclose(d1(x).asnumpy(), d2(x).asnumpy())


def test_block_naming():
    with mx.name.NameManager():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(4), nn.Dense(2))
    names = list(net.collect_params().keys())
    assert all(n.startswith(net.prefix) for n in names)
    assert len(names) == 4


def test_trainer_converges():
    X, y = _toy()
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    for _ in range(40):
        with autograd.record():
            loss = loss_fn(net(X), y)
        loss.backward()
        trainer.step(X.shape[0])
    assert float(loss.mean().asscalar()) < 0.1


def test_hybridize_matches_imperative():
    X, _ = _toy()
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    imp = net(X).asnumpy()
    net.hybridize()
    hyb = net(X).asnumpy()
    np.testing.assert_allclose(imp, hyb, rtol=1e-5, atol=1e-6)


def test_hybridize_trains():
    X, y = _toy()
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    for _ in range(40):
        with autograd.record():
            loss = loss_fn(net(X), y)
        loss.backward()
        trainer.step(X.shape[0])
    assert float(loss.mean().asscalar()) < 0.1


def test_batchnorm_aux_updates():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.Flatten(), nn.Dense(2))
    net.initialize()
    x = nd.array(np.random.randn(4, 3, 8, 8).astype(np.float32))
    with autograd.record():
        net(x)
    rm = net[1].running_mean.data().asnumpy()
    assert not np.allclose(rm, 0)


def test_save_load_params():
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net.initialize(mx.init.Xavier())
    x = nd.ones((2, 4))
    out1 = net(x).asnumpy()
    with tempfile.TemporaryDirectory() as tmp:
        fname = os.path.join(tmp, "net.params")
        net.save_params(fname)
        net2 = nn.HybridSequential(prefix="model_")
        with net2.name_scope():
            net2.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
        net2.load_params(fname)
        np.testing.assert_allclose(net2(x).asnumpy(), out1, rtol=1e-6)


def test_losses():
    pred = nd.array(np.random.randn(8, 4).astype(np.float32))
    label = nd.array(np.random.randint(0, 4, 8).astype(np.float32))
    for loss_fn in [gluon.loss.SoftmaxCrossEntropyLoss(),
                    gluon.loss.L2Loss(), gluon.loss.L1Loss(),
                    gluon.loss.HuberLoss()]:
        if isinstance(loss_fn, gluon.loss.SoftmaxCrossEntropyLoss):
            val = loss_fn(pred, label)
        else:
            val = loss_fn(pred, nd.array(
                np.random.randn(8, 4).astype(np.float32)))
        assert val.shape == (8,)
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    v = bce(nd.array(np.random.randn(8).astype(np.float32)),
            nd.array((np.random.randn(8) > 0).astype(np.float32)))
    assert np.isfinite(v.asnumpy()).all()


def test_softmax_ce_loss_matches_numpy():
    logits = np.random.randn(6, 3).astype(np.float32)
    labels = np.random.randint(0, 3, 6)
    l = gluon.loss.SoftmaxCrossEntropyLoss()(
        nd.array(logits), nd.array(labels.astype(np.float32))).asnumpy()
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    expect = -np.log(p[np.arange(6), labels])
    np.testing.assert_allclose(l, expect, rtol=1e-5, atol=1e-6)


def test_dataset_dataloader():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    X = np.random.randn(20, 4).astype(np.float32)
    y = np.arange(20).astype(np.float32)
    ds = ArrayDataset(X, y)
    assert len(ds) == 20
    dl = DataLoader(ds, batch_size=6, last_batch="keep")
    batches = list(dl)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 4)
    assert batches[-1][0].shape == (2, 4)
    dl2 = DataLoader(ds, batch_size=6, last_batch="discard",
                     num_workers=2)
    assert len(list(dl2)) == 3
    # transform
    ds2 = ds.transform_first(lambda x: x * 2)
    x0, y0 = ds2[0]
    np.testing.assert_allclose(x0, X[0] * 2, rtol=1e-6)


def test_vision_synthetic_dataset():
    from mxnet_tpu.gluon.data.vision import SyntheticImageDataset
    ds = SyntheticImageDataset(length=16, shape=(8, 8, 3))
    img, label = ds[0]
    assert img.shape == (8, 8, 3)
    assert 0 <= int(label) < 10


def test_model_zoo_forward():
    from mxnet_tpu.gluon.model_zoo import get_model
    x = nd.array(np.random.randn(2, 3, 32, 32).astype(np.float32))
    for name in ["resnet18_v1", "resnet18_v2", "mobilenet0.25"]:
        net = get_model(name, classes=10)
        net.initialize()
        assert net(x).shape == (2, 10), name


def test_rnn_cells():
    for cell_cls, n_states in [(gluon.rnn.RNNCell, 1),
                               (gluon.rnn.LSTMCell, 2),
                               (gluon.rnn.GRUCell, 1)]:
        cell = cell_cls(8, input_size=4)
        cell.initialize()
        seq = nd.array(np.random.randn(2, 5, 4).astype(np.float32))
        outs, states = cell.unroll(5, seq, layout="NTC",
                                   merge_outputs=True)
        assert outs.shape == (2, 5, 8)
        assert len(states) == n_states


def test_rnn_layers():
    seq = nd.array(np.random.randn(3, 5, 8).astype(np.float32))
    lstm = gluon.rnn.LSTM(16, num_layers=2, layout="NTC", input_size=8)
    lstm.initialize()
    assert lstm(seq).shape == (3, 5, 16)
    bi = gluon.rnn.GRU(16, bidirectional=True, layout="NTC", input_size=8)
    bi.initialize()
    assert bi(seq).shape == (3, 5, 32)


def test_rnn_trains():
    seq = nd.array(np.random.randn(4, 6, 8).astype(np.float32))
    y = nd.array((np.random.randn(4) > 0).astype(np.float32))
    cell = gluon.rnn.LSTMCell(16, input_size=8)
    dense = nn.Dense(2)
    cell.initialize()
    dense.initialize()
    params = gluon.ParameterDict()
    params.update(cell.collect_params())
    params.update(dense.collect_params())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    first = None
    for i in range(30):
        with autograd.record():
            outs, _ = cell.unroll(6, seq, layout="NTC",
                                  merge_outputs=False)
            loss = loss_fn(dense(outs[-1]), y)
        loss.backward()
        trainer.step(4)
        if first is None:
            first = float(loss.mean().asscalar())
    last = float(loss.mean().asscalar())
    assert last < first


def test_split_and_load():
    from mxnet_tpu.gluon.utils import split_and_load, clip_global_norm
    data = nd.array(np.arange(24).reshape(8, 3).astype(np.float32))
    parts = split_and_load(data, [mx.cpu(0), mx.cpu(1)])
    assert len(parts) == 2 and parts[0].shape == (4, 3)
    arrs = [nd.ones((4,)) * 10, nd.ones((3,)) * 10]
    norm = clip_global_norm(arrs, 1.0)
    assert norm > 1.0
    total = sum(float((a * a).sum().asscalar()) for a in arrs)
    assert total <= 1.01


def test_symbol_block():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=8)
    net = mx.sym.Activation(net, act_type="relu")
    sb = gluon.SymbolBlock(net, mx.sym.Variable("data"))
    sb.params.initialize()
    # deferred shapes resolve from the wrapped symbol on first forward
    out = sb(nd.ones((2, 4)))
    assert out.shape == (2, 8)


def test_symbol_block_wraps_catalog_model():
    """SymbolBlock over a models/ builder: strip the training head and
    run feature extraction (the reference fine-tuning workflow)."""
    from mxnet_tpu.models import mobilenet
    feat = mobilenet.get_symbol(10, multiplier=0.25).get_internals()[
        "fc_output"]
    blk = gluon.SymbolBlock(feat, [mx.sym.Variable("data")])
    blk.collect_params().initialize(mx.init.Xavier())
    out = blk(nd.ones((2, 3, 64, 64)))
    assert out.shape == (2, 10)


def test_initialize_respects_global_initializer():
    """Regression: net.initialize(Xavier()) must actually apply Xavier,
    not the hardcoded Uniform(0.07) fallback."""
    dense = nn.Dense(64, in_units=256)
    dense.initialize(mx.init.Xavier())
    w = dense.weight.data().asnumpy()
    # Xavier-uniform bound for (64,256): sqrt(3/160) ~ 0.137 > 0.07
    assert np.abs(w).max() > 0.08
    dense2 = nn.Dense(64, in_units=256)
    dense2.initialize(mx.init.Zero())
    np.testing.assert_allclose(dense2.weight.data().asnumpy(), 0)


def test_param_load_casts_dtype():
    p = gluon.Parameter("w", shape=(4,), dtype=np.float32)
    p._load_init(nd.array(np.arange(4, dtype=np.float64)), None)
    assert p.data().dtype == np.float32


def test_symbol_block_nests_in_hybridized_parent():
    """A SymbolBlock inside a hybridized HybridSequential: the parent's
    trace composes the wrapped graph onto its input, and hybridize's
    cache clear must not drop the wrapped symbol (it is the block's
    definition, not re-derivable)."""
    inner = mx.sym.Activation(mx.sym.FullyConnected(
        mx.sym.Variable("data"), name="fc_in", num_hidden=8),
        act_type="relu")
    sb = gluon.SymbolBlock(inner, [mx.sym.Variable("data")])
    net = gluon.nn.HybridSequential()
    net.add(sb, gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    assert net(nd.ones((2, 6))).shape == (2, 4)


def test_unrecognized_param_name_uses_default_fill():
    """Params whose name matches no suffix (e.g. a PReLU 'alpha') fill
    with the default initializer's weight rule instead of raising."""
    p = gluon.Parameter("alpha", shape=(3,))
    p.initialize()
    assert p.data().shape == (3,)


def test_hybridblock_export_to_symbolic_surfaces():
    """gluon -> export -> (Predictor, TrainStep): the checkpoint-layout
    bridge from imperative model authoring to the deployment and SPMD
    training paths (reference HybridBlock.export)."""
    import os
    import tempfile

    import jax

    from mxnet_tpu.parallel import data_parallel_mesh, make_train_step

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"),
            gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    X = np.random.RandomState(0).randn(8, 12).astype(np.float32)
    want = net(nd.array(X)).asnumpy()

    prefix = os.path.join(tempfile.mkdtemp(), "gluon_net")
    net.export(prefix)

    # deployment path: load_checkpoint -> Predictor reproduces outputs
    pred = mx.predictor.load_checkpoint_predictor(prefix, 0)
    got = pred.forward(X)[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # SPMD path: compose a loss head, adopt the exported weights via
    # the public init_state(arg_params=...) surface, train on a mesh
    sym_net, arg_params, aux_params = mx.model.load_checkpoint(prefix, 0)
    loss = mx.sym.SoftmaxOutput(sym_net, name="softmax")
    step = make_train_step(loss, mesh=data_parallel_mesh(),
                           optimizer="sgd",
                           optimizer_params={"rescale_grad": 1.0 / 8})
    state = step.init_state(mx.init.Xavier(),
                            {"data": X.shape, "softmax_label": (8,)},
                            arg_params=arg_params,
                            aux_params=aux_params)
    # exported weights were adopted verbatim (name-counter agnostic:
    # the prefix depends on how many blocks earlier tests created)
    a_weight = next(k for k in arg_params if k.endswith("weight"))
    np.testing.assert_allclose(np.asarray(state[0][a_weight]),
                               arg_params[a_weight].asnumpy())
    y = np.random.RandomState(1).randint(0, 4, 8).astype(np.float32)
    batch = step.place_batch({"data": X, "softmax_label": y})
    state, outs = step(state, batch, 0.1, jax.random.PRNGKey(0))
    assert np.isfinite(np.asarray(outs[0])).all()

    # un-traced blocks refuse to export
    fresh = gluon.nn.HybridSequential()
    fresh.add(gluon.nn.Dense(2))
    fresh.initialize()
    with pytest.raises(RuntimeError, match="hybridize"):
        fresh.export(prefix + "_x")
