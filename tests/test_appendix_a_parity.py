"""SURVEY.md Appendix A walked as a test: every operator name the
reference registers (grep over NNVM_REGISTER_OP / MXNET_REGISTER_OP_
PROPERTY in /root/reference/src/operator, transcribed in SURVEY.md
Appendix A) must resolve on BOTH mx.sym and mx.nd — the analogue of the
reference's auto-generation guarantee (python/mxnet/base.py:381 creates
one Python function per registered op, so the reference could never
have a name gap).

Names the rebuild deliberately does not carry are in EXPECTED_ABSENT
with the SURVEY/VERDICT justification; everything else missing is a
straight failure.
"""
import pytest

import mxnet_tpu as mx

# -- Appendix A, transcribed -------------------------------------------------

LEGACY_LAYERS = [
    "Activation", "BatchNorm", "BatchNorm_v1", "BilinearSampler",
    "Concat", "Convolution", "Convolution_v1", "Correlation", "Crop",
    "Deconvolution", "Dropout", "FullyConnected", "GridGenerator",
    "IdentityAttachKLSparseReg", "InstanceNorm", "L2Normalization",
    "LRN", "LeakyReLU", "LinearRegressionOutput",
    "LogisticRegressionOutput", "MAERegressionOutput", "MakeLoss",
    "Pad", "Pooling", "Pooling_v1", "RNN", "ROIPooling", "SVMOutput",
    "SequenceLast", "SequenceMask", "SequenceReverse", "SliceChannel",
    "Softmax", "SoftmaxActivation", "SoftmaxOutput",
    "SpatialTransformer", "SwapAxis", "UpSampling",
]

CONTRIB_LEGACY = [
    "_contrib_CTCLoss", "_contrib_DeformableConvolution",
    "_contrib_DeformablePSROIPooling", "_contrib_MultiBoxDetection",
    "_contrib_MultiBoxPrior", "_contrib_MultiBoxTarget",
    "_contrib_MultiProposal", "_contrib_PSROIPooling",
    "_contrib_Proposal", "_contrib_count_sketch", "_contrib_fft",
    "_contrib_ifft",
]

NNVM_CORE = [
    "Cast", "Custom", "Embedding", "Flatten", "Reshape",
]

TENSOR = [
    "_arange", "_ones", "_zeros", "zeros_like", "ones_like", "_copy",
    "BlockGrad", "make_loss", "_identity_with_attr_like_rhs", "clip",
    "repeat", "tile", "reverse", "stack", "expand_dims", "slice",
    "_slice_assign", "_crop_assign_scalar", "slice_axis", "dot",
    "batch_dot", "transpose", "norm", "topk", "sort", "argsort",
    "argmax", "argmin", "argmax_channel", "pick", "take", "batch_take",
    "one_hot", "where", "cast_storage", "_sparse_retain", "_square_sum",
    "sum", "mean", "prod", "nansum", "nanprod", "max", "min",
    "broadcast_axis", "broadcast_to", "softmax", "log_softmax",
    "softmax_cross_entropy", "smooth_l1",
]

# "elemwise binary (+`_scalar`, `broadcast_*`, sparse variants):
# add/sub/mul/div/mod, _grad_add, maximum/minimum, power/rpower, hypot,
# equal/..., elemwise_{add,sub,mul,div}, add_n"
ELEMWISE_BINARY = (
    ["elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
     "add_n", "_grad_add",
     "maximum", "minimum", "hypot",
     "equal", "not_equal", "greater", "greater_equal", "lesser",
     "lesser_equal"]
    + ["broadcast_%s" % n for n in
       ("add", "sub", "mul", "div", "mod", "power", "maximum",
        "minimum", "hypot", "equal", "not_equal", "greater",
        "greater_equal", "lesser", "lesser_equal")]
    + ["_%s_scalar" % n for n in
       ("plus", "minus", "rminus", "mul", "div", "rdiv", "mod", "rmod",
        "power", "rpower", "maximum", "minimum", "hypot", "equal",
        "not_equal", "greater", "greater_equal", "lesser",
        "lesser_equal")]
)

UNARY_MATH = [
    "abs", "sign", "negative", "reciprocal", "rcbrt", "cbrt", "sqrt",
    "rsqrt", "square", "exp", "expm1", "log", "log10", "log1p", "log2",
    "sin", "cos", "tan", "sinh", "cosh", "tanh", "arcsin", "arccos",
    "arctan", "arcsinh", "arccosh", "arctanh", "degrees", "radians",
    "gamma", "gammaln", "relu", "sigmoid", "ceil", "floor", "rint",
    "round", "fix", "trunc",
]

RANDOM = (
    ["_random_%s" % n for n in
     ("uniform", "normal", "exponential", "gamma", "poisson",
      "negative_binomial", "generalized_negative_binomial")]
    + ["_sample_%s" % n for n in
       ("uniform", "normal", "exponential", "gamma", "poisson",
        "negative_binomial", "generalized_negative_binomial")]
    + ["sample_multinomial"]
)

LINALG = ["_linalg_%s" % n for n in
          ("gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "syrk",
           "gelqf", "sumlogdiag")]

OPTIMIZER = [
    "sgd_update", "sgd_mom_update", "mp_sgd_update",
    "mp_sgd_mom_update", "adam_update", "rmsprop_update",
    "rmspropalex_update", "ftrl_update",
]

CONTRIB_NNVM = ["_contrib_quantize", "_contrib_dequantize"]

# .add_alias legacy names called out in Appendix A
ALIASES = ["identity", "stop_gradient"]

ALL_NAMES = (LEGACY_LAYERS + CONTRIB_LEGACY + NNVM_CORE + TENSOR
             + ELEMWISE_BINARY + UNARY_MATH + RANDOM + LINALG
             + OPTIMIZER + CONTRIB_NNVM + ALIASES)

# -- sanctioned drops (SURVEY section 7 design stance / VERDICT r3) ----------

EXPECTED_ABSENT = {
    # N30 plugins: caffe/torch/warpctc bridges are meaningless without
    # the bridged frameworks; VERDICT r3 counts the drop as acceptable
    "CaffeLoss", "CaffeOp", "TorchCriterion", "TorchModule", "WarpCTC",
    # cuDNN-internal registration: the cuDNN special-case dissolves
    # into XLA's conv (SURVEY N10 "absorbed"); user-facing BatchNorm /
    # BatchNorm_v1 both bind
    "CuDNNBatchNorm",
    # engine-internal node inserted by the PlaceDevice pass, never a
    # user-callable op; device movement is GSPMD sharding here
    # (executor.py ctx_group -> sharding constraints)
    "_CrossDeviceCopy",
    # legacy pre-0.9 python-op bridges superseded IN THE REFERENCE by
    # Custom (src/operator/custom/custom.cc); the rebuild carries
    # Custom only
    "_NDArray", "_Native",
}


def _resolves(ns, name):
    try:
        return callable(getattr(ns, name))
    except AttributeError:
        return False


@pytest.mark.parametrize("name", sorted(set(ALL_NAMES)))
def test_name_resolves(name):
    missing = [repr(ns_name) for ns_name, ns in
               (("mx.sym", mx.sym), ("mx.nd", mx.nd))
               if not _resolves(ns, name)]
    assert not missing, "%s does not resolve on %s" % (name, missing)


@pytest.mark.parametrize("name", sorted(EXPECTED_ABSENT))
def test_documented_drops_stay_dropped(name):
    """If one of these starts resolving, it graduated — move it out of
    EXPECTED_ABSENT so the parity list tracks reality."""
    assert not _resolves(mx.sym, name), (
        "%s now resolves; remove it from EXPECTED_ABSENT" % name)
