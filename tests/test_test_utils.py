"""test_utils surface tests (reference test_utils.py helpers)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, test_utils as tu


def test_check_symbolic_forward_backward():
    x = mx.sym.Variable("x")
    y = x * 3.0 + 1.0
    loc = {"x": np.ones((2, 2), "float32")}
    tu.check_symbolic_forward(y, loc, [np.full((2, 2), 4.0)])
    grads = tu.check_symbolic_backward(
        y, loc, [np.ones((2, 2), "float32")],
        {"x": np.full((2, 2), 3.0)})
    assert "x" in grads


def test_check_symbolic_backward_detects_mismatch():
    x = mx.sym.Variable("x")
    y = x * 3.0
    with pytest.raises(AssertionError):
        tu.check_symbolic_backward(
            y, {"x": np.ones((2,), "float32")},
            [np.ones((2,), "float32")],
            {"x": np.full((2,), 99.0)})


def test_rand_sparse_ndarray():
    arr, (vals, idx) = tu.rand_sparse_ndarray((8, 3), "row_sparse",
                                              density=0.5)
    assert arr.stype == "row_sparse"
    assert vals.shape[0] == idx.shape[0] == arr.nnz
    arr, parts = tu.rand_sparse_ndarray((6, 4), "csr")
    assert len(parts) == 3


def test_check_speed_returns_positive():
    x = mx.sym.Variable("x")
    t = tu.check_speed(sym=x + 1.0,
                       location={"x": np.ones((4, 4), "float32")}, N=3)
    assert t > 0
    t = tu.check_speed(sym=x * 2.0,
                       location={"x": np.ones((4, 4), "float32")},
                       N=2, typ="whole")
    assert t > 0
    with pytest.raises(ValueError):
        tu.check_speed(sym=x, location={}, typ="wrong")


def test_check_symbolic_backward_with_aux():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn", fix_gamma=False)
    loc = {"data": np.random.RandomState(0).randn(4, 3).astype("f"),
           "bn_gamma": np.ones(3, "float32"),
           "bn_beta": np.zeros(3, "float32")}
    aux = {"bn_moving_mean": np.zeros(3, "float32"),
           "bn_moving_var": np.ones(3, "float32")}
    grads = tu.check_symbolic_backward(
        bn, loc, [np.ones((4, 3), "float32")], {}, aux_states=aux)
    assert "data" in grads


def test_same_and_discard_stderr():
    assert tu.same([1, 2], np.array([1, 2]))
    assert not tu.same([1], [2])
    import sys
    with tu.discard_stderr():
        print("hidden", file=sys.stderr)


def test_kvstore_server_role_shim(monkeypatch):
    from mxnet_tpu import kvstore_server
    monkeypatch.setenv("DMLC_ROLE", "worker")
    assert kvstore_server._init_kvstore_server_module() is False
