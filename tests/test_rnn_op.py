"""Fused RNN op vs numpy step loops (the cuDNN-RNN replacement,
reference src/operator/rnn-inl.h semantics; cuDNN packed-blob layout)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops.rnn_op import rnn_param_size


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_rnn_tanh_single_layer():
    T, N, I, H = 4, 2, 3, 5
    rng = np.random.RandomState(0)
    x = rng.randn(T, N, I).astype(np.float32)
    n_par = rnn_param_size("rnn_tanh", I, H, 1, False)
    par = rng.uniform(-0.3, 0.3, n_par).astype(np.float32)
    h0 = np.zeros((1, N, H), np.float32)

    out = nd.RNN(nd.array(x), nd.array(par), nd.array(h0),
                 state_size=H, num_layers=1, mode="rnn_tanh").asnumpy()

    # unpack blob: w_i2h (H,I), w_h2h (H,H), then b_i2h (H,), b_h2h (H,)
    pos = 0
    w_i2h = par[pos:pos + H * I].reshape(H, I); pos += H * I
    w_h2h = par[pos:pos + H * H].reshape(H, H); pos += H * H
    b_i2h = par[pos:pos + H]; pos += H
    b_h2h = par[pos:pos + H]
    h = np.zeros((N, H), np.float32)
    want = []
    for t in range(T):
        h = np.tanh(x[t] @ w_i2h.T + b_i2h + h @ w_h2h.T + b_h2h)
        want.append(h)
    np.testing.assert_allclose(out, np.stack(want), rtol=1e-5, atol=1e-5)


def test_lstm_single_layer_states():
    T, N, I, H = 3, 2, 4, 3
    rng = np.random.RandomState(1)
    x = rng.randn(T, N, I).astype(np.float32)
    n_par = rnn_param_size("lstm", I, H, 1, False)
    par = rng.uniform(-0.3, 0.3, n_par).astype(np.float32)
    h0 = np.zeros((1, N, H), np.float32)
    c0 = np.zeros((1, N, H), np.float32)

    outs = nd.RNN(nd.array(x), nd.array(par), nd.array(h0), nd.array(c0),
                  state_size=H, num_layers=1, mode="lstm",
                  state_outputs=True)
    y, hT, cT = [o.asnumpy() for o in outs]

    pos = 0
    w_i2h = par[pos:pos + 4 * H * I].reshape(4 * H, I); pos += 4 * H * I
    w_h2h = par[pos:pos + 4 * H * H].reshape(4 * H, H); pos += 4 * H * H
    b_i2h = par[pos:pos + 4 * H]; pos += 4 * H
    b_h2h = par[pos:pos + 4 * H]
    h = np.zeros((N, H), np.float32)
    c = np.zeros((N, H), np.float32)
    want = []
    for t in range(T):
        g = x[t] @ w_i2h.T + b_i2h + h @ w_h2h.T + b_h2h
        i_g, f_g, g_g, o_g = np.split(g, 4, axis=1)
        i_g, f_g, o_g = _sigmoid(i_g), _sigmoid(f_g), _sigmoid(o_g)
        c = f_g * c + i_g * np.tanh(g_g)
        h = o_g * np.tanh(c)
        want.append(h)
    np.testing.assert_allclose(y, np.stack(want), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hT[0], h, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cT[0], c, rtol=1e-5, atol=1e-5)


def test_bidirectional_output_width():
    T, N, I, H = 3, 2, 4, 3
    rng = np.random.RandomState(2)
    x = rng.randn(T, N, I).astype(np.float32)
    n_par = rnn_param_size("gru", I, H, 2, True)
    par = rng.uniform(-0.3, 0.3, n_par).astype(np.float32)
    h0 = np.zeros((4, N, H), np.float32)  # L*D = 2*2
    out = nd.RNN(nd.array(x), nd.array(par), nd.array(h0),
                 state_size=H, num_layers=2, bidirectional=True,
                 mode="gru").asnumpy()
    assert out.shape == (T, N, 2 * H)
    assert np.all(np.isfinite(out))
