"""Prefill/decode disaggregation (docs/serving.md §disaggregated
prefill): KV-cache handoff over the wire.

Load-bearing acceptance gate: (remote prefill → export_kv_rows → wire
→ import_kv_rows → decode) emits token-for-token what a
single-process ``Generator.generate`` emits — for f32, bf16 and int8
(quantize_kv) caches, GQA included — with ZERO prefill graph calls on
the decode side (the ``prefills`` stat), and a mid-handoff injected
disconnect replays the pure prefill to the identical blob with
exactly one admit.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config as mxconfig
from mxnet_tpu.generation import Generator, kv_blob_nbytes
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.models import transformer
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.parallel.resilience import (FaultInjector,
                                           install_fault_injector)
from mxnet_tpu.serve import (ContinuousDecoder, PrefillEngine,
                             ServeRouter, ServeServer)
from mxnet_tpu.serve.decode import drain_timeout

pytestmark = pytest.mark.serve

V, L, H, DIM, T, B = 50, 2, 2, 32, 24, 3


def _params(seed=0, num_kv_heads=None):
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T,
                                 num_kv_heads=num_kv_heads)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(seed)
    state = step.init_state(Xavier(), {"data": (2, 12),
                                       "softmax_label": (2, 12)})
    return state[0]


@pytest.fixture(scope="module")
def params():
    return _params()


def _gen(params, batch_size, **kw):
    return Generator(params, V, T, num_layers=L, num_heads=H, dim=DIM,
                     batch_size=batch_size, **kw)


def _ragged(rng, n=4):
    # two DISTINCT prompt lengths only: ragged coverage without a
    # fresh XLA prefill specialization per sequence (tier-1 rides the
    # wall-clock budget; every extra length is two compiles)
    prompts = [rng.randint(0, V, (p,)) for p in (4, 6, 4, 6, 4)[:n]]
    maxnew = [8, 3, 6, 5, 4][:n]
    return prompts, maxnew


class TestHandoffRoundTrip:
    def _roundtrip_parity(self, params, **genkw):
        """ACCEPTANCE body: prefill on one engine, export, import into
        a separate pool, decode — token-for-token vs single-process
        generate; admission runs zero prefill graph calls."""
        single = _gen(params, 1, **genkw)
        pre = PrefillEngine(_gen(params, 2, **genkw))
        rng = np.random.RandomState(3)
        prompts, maxnew = _ragged(rng, 5)   # > B: slot turnover too
        with _gen(params, B, **genkw).serving_decoder() as dec:
            futs = [dec.submit(p, n, eos_id=0, handoff=pre.prefill(p))
                    for p, n in zip(prompts, maxnew)]
            got = [f.result(120.0) for f in futs]
            st = dec.stats()
        assert st["prefills"] == 0          # scatter-only admission
        assert st["imported"] == len(prompts)
        assert st["finished"] == len(prompts) > B
        for p, n, g in zip(prompts, maxnew, got):
            np.testing.assert_array_equal(
                g, single.generate(p[None], n, eos_id=0)[0])

    def test_greedy_parity_f32(self, params):
        self._roundtrip_parity(params)

    # ~10 s on the 1-core tier-1 host — slow tier; f32 (fast, above)
    # pins the round-trip contract and the bf16 row dtype is preserved
    # bit-for-bit by the same export path test_prefill_is_pure_and_
    # blob_exact checks
    @pytest.mark.slow
    def test_greedy_parity_bf16(self, params):
        self._roundtrip_parity(params, dtype="bfloat16")

    def test_greedy_parity_int8_kv_gqa(self):
        """int8 caches + GQA in one pool: the handoff ships int8 rows
        AND their per-token f32 scale rows, at kv_heads=1 (covers the
        plain-int8 path too — same scatter, more rows)."""
        params = _params(seed=5, num_kv_heads=1)
        self._roundtrip_parity(params, quantize_kv=True,
                               num_kv_heads=1)

    def test_sampled_parity(self, params):
        """The handoff first token consumes the request PRNG stream's
        first split on the PREFILL side; the decode side continues the
        stream — together exactly generate()'s key discipline."""
        single = _gen(params, 1)
        pre = PrefillEngine(_gen(params, 2))
        prompt = np.random.RandomState(9).randint(0, V, (5,))
        with _gen(params, B).serving_decoder() as dec:
            h = pre.prefill(prompt, temperature=0.8, top_k=5, seed=42)
            got = dec.submit(prompt, 6, temperature=0.8, top_k=5,
                             seed=42, handoff=h).result(120.0)
        want = single.generate(prompt[None], 6, temperature=0.8,
                               top_k=5, seed=42)[0]
        np.testing.assert_array_equal(got, want)

    def test_prefill_is_pure_and_blob_exact(self, params):
        """Replay safety rests on purity: the same prompt + seed lands
        the bit-identical reply, and the exported rows equal the
        prefill aux's own rows (device-roundtrip-exact)."""
        gen = _gen(params, 2)
        pre = PrefillEngine(gen)
        prompt = np.arange(1, 7)
        h1, h2 = pre.prefill(prompt), pre.prefill(prompt)
        assert h1["first_token"] == h2["first_token"]
        assert h1["pos"] == h2["pos"] == 6
        for name in h1["kv_blob"]["rows"]:
            a, b = h1["kv_blob"]["rows"][name], h2["kv_blob"]["rows"][name]
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b)
        # export slices the aux bit-for-bit
        rows = np.stack([prompt, prompt]).astype(np.float32)
        _, aux = gen._forward(gen._fresh_aux(), rows, 0)
        blob = gen.export_kv_rows(aux, 0, 6)
        for name, arr in blob["rows"].items():
            np.testing.assert_array_equal(
                arr, np.asarray(aux[name][0, :, :6]))

    def test_int8_blob_smaller_than_f32(self, params):
        """int8 rows + f32 per-token scales undercut the float blob
        (the ≤0.55x-vs-bf16 acceptance figure is measured at hd=128
        by bench_serve.py --disagg; at this toy hd the ordering still
        must hold)."""
        p = np.arange(1, 9)
        b_f32 = PrefillEngine(_gen(params, 1)).prefill(p)
        b_q8 = PrefillEngine(
            _gen(params, 1, quantize_kv=True)).prefill(p)
        assert kv_blob_nbytes(b_q8["kv_blob"]) < \
            kv_blob_nbytes(b_f32["kv_blob"])

    def test_blob_validation_is_loud(self, params):
        gen = _gen(params, 2)
        pre = PrefillEngine(gen)
        prompt = np.arange(1, 6)
        h = pre.prefill(prompt)
        with _gen(params, B).serving_decoder() as dec:
            with pytest.raises(ValueError, match="exactly the prompt"):
                dec.submit(np.arange(1, 5), 3, handoff=h)  # wrong P
            with pytest.raises(ValueError, match="first_token"):
                dec.submit(prompt, 3, handoff={"kv_blob": 1})
        # a quantized blob must not scatter into a float pool
        hq = PrefillEngine(
            _gen(params, 1, quantize_kv=True)).prefill(prompt)
        with _gen(params, B).serving_decoder() as dec:
            with pytest.raises(ValueError,
                               match="do not match this pool"):
                dec.submit(prompt, 3, handoff=hq)
        # export-side validation
        rows = np.stack([prompt, prompt]).astype(np.float32)
        _, aux = gen._forward(gen._fresh_aux(), rows, 0)
        with pytest.raises(ValueError, match="row 7 out of range"):
            gen.export_kv_rows(aux, 7, 5)
        with pytest.raises(ValueError, match="pos 99 out of range"):
            gen.export_kv_rows(aux, 0, 99)


class TestWire:
    def _fleet(self, params, **genkw):
        pre_eng = PrefillEngine(_gen(params, 2, **genkw))
        dec_eng = ContinuousDecoder(_gen(params, B, **genkw))
        s1, s2 = ServeServer(pre_eng), ServeServer(dec_eng)
        router = ServeRouter(poll_ms=0)
        router.add_replica(s1.host, s1.port, name="prefill0")
        router.add_replica(s2.host, s2.port, name="decode0")
        router.poll_now()
        return pre_eng, dec_eng, s1, s2, router

    def test_router_disagg_parity(self, params):
        """ACCEPTANCE: the full wire path — role-aware dispatch,
        prefill frame, blob shipped with the admit — matches
        single-process generate; the decode replica never prefills."""
        single = _gen(params, 1)
        pre_eng, dec_eng, s1, s2, router = self._fleet(params)
        try:
            assert {r["role"] for r in router.replicas().values()} \
                == {"prefill", "decode"}
            rng = np.random.RandomState(7)
            prompts, maxnew = _ragged(rng, 4)
            for p, n in zip(prompts, maxnew):
                out = router.generate(p, n, eos_id=0, session="sA")
                np.testing.assert_array_equal(
                    np.asarray(out),
                    single.generate(p[None], n, eos_id=0)[0])
            assert dec_eng.stats()["prefills"] == 0
            assert dec_eng.stats()["imported"] == len(prompts)
            assert pre_eng.stats()["prefills"] == len(prompts)
            # the session pinned to the decode replica, not prefill
            assert router.sessions() == {"sA": "decode0"}
        finally:
            router.close(); s1.close(); s2.close(); dec_eng.close()

    def test_mid_handoff_disconnect_replays_one_admit(self, params):
        """ACCEPTANCE: a disconnect torn into the 2nd prefill frame
        replays the pure prefill on a fresh connection — the replayed
        blob is identical (purity, pinned above), the decode side
        admits exactly once per request, tokens exact."""
        single = _gen(params, 1)
        pre_eng, dec_eng, s1, s2, router = self._fleet(params)
        inj = install_fault_injector(
            FaultInjector("prefill_send:disconnect@2"))
        try:
            rng = np.random.RandomState(11)
            prompts, maxnew = _ragged(rng, 2)
            for p, n in zip(prompts, maxnew):
                out = router.generate(p, n, eos_id=0)
                np.testing.assert_array_equal(
                    np.asarray(out),
                    single.generate(p[None], n, eos_id=0)[0])
            assert inj.fired == [("prefill_send", 2, "disconnect")]
            st = dec_eng.stats()
            assert st["admitted"] == st["imported"] == len(prompts)
            assert st["prefills"] == 0
        finally:
            install_fault_injector(None)
            router.close(); s1.close(); s2.close(); dec_eng.close()

    def test_decode_only_fleet_stays_colocated(self, params):
        """No prefill-role replica → today's colocated path: the
        admitting replica prefills locally, zero imports."""
        single = _gen(params, 1)
        dec_eng = ContinuousDecoder(_gen(params, B))
        srv = ServeServer(dec_eng)
        router = ServeRouter(poll_ms=0)
        router.add_replica(srv.host, srv.port, name="colo0")
        router.poll_now()
        try:
            p = np.random.RandomState(13).randint(0, V, (5,))
            out = router.generate(p, 6, eos_id=0)
            np.testing.assert_array_equal(
                np.asarray(out), single.generate(p[None], 6,
                                                 eos_id=0)[0])
            st = dec_eng.stats()
            assert st["imported"] == 0 and st["prefills"] >= 1
        finally:
            router.close(); srv.close(); dec_eng.close()

    def test_generate_prefers_decode_replicas_in_mixed_fleet(self,
                                                            params):
        """A mixed batch+decode fleet (no prefill role): generate
        frames must land on the decode replica even when the batch
        replica is least-loaded — a 'batch' neighbor has no
        handle_generate() and its typed error would fail the request
        while a decode-capable replica sits idle."""
        from mxnet_tpu.serve import ServeEngine

        class _Echo:
            def forward(self, *arrays):
                return [np.asarray(arrays[0])]
        single = _gen(params, 1)
        eng = ServeEngine(_Echo(), buckets=(1,), max_wait_ms=0.0,
                          feature_shapes=[(4,)], install_sigterm=False)
        dec_eng = ContinuousDecoder(_gen(params, B))
        s1, s2 = ServeServer(eng), ServeServer(dec_eng)
        router = ServeRouter(poll_ms=0)
        router.add_replica(s1.host, s1.port, name="batch0")
        router.add_replica(s2.host, s2.port, name="decode0")
        router.poll_now()
        try:
            p = np.arange(1, 5)
            out = router.generate(p, 4, eos_id=0)
            np.testing.assert_array_equal(
                np.asarray(out), single.generate(p[None], 4,
                                                 eos_id=0)[0])
        finally:
            router.close(); s1.close(); s2.close()
            eng.close(); dec_eng.close()

    def test_caller_supplied_handoff_passes_through_router(self,
                                                           params):
        """The replica-surface contract: a client that already paid
        its remote prefill ships the blob through the router-fronted
        endpoint and the router must NOT prefill again — the blob
        admits scatter-only on the decode replica."""
        single = _gen(params, 1)
        pre = PrefillEngine(_gen(params, 2))
        dec_eng = ContinuousDecoder(_gen(params, B))
        srv = ServeServer(dec_eng)
        router = ServeRouter(poll_ms=0)
        router.add_replica(srv.host, srv.port, name="decode0")
        router.poll_now()
        try:
            p = np.arange(1, 6)
            h = pre.prefill(p)
            out = router.generate(p, 4, eos_id=0, handoff=h)
            np.testing.assert_array_equal(
                np.asarray(out), single.generate(p[None], 4,
                                                 eos_id=0)[0])
            st = dec_eng.stats()
            assert st["imported"] == 1 and st["prefills"] == 0
        finally:
            router.close(); srv.close(); dec_eng.close()

    def test_infer_never_routes_to_prefill_replicas(self, params):
        """A prefill replica cannot answer infer — role-aware dispatch
        must keep ordinary traffic off it even when it is the
        least-loaded replica by score."""
        from mxnet_tpu.serve import ServeEngine

        class _Echo:
            def forward(self, *arrays):
                return [np.asarray(arrays[0])]
        eng = ServeEngine(_Echo(), buckets=(1, 2), max_wait_ms=0.0,
                          feature_shapes=[(4,)], install_sigterm=False)
        pre_eng = PrefillEngine(_gen(params, 1))
        s1, s2 = ServeServer(pre_eng), ServeServer(eng)
        router = ServeRouter(poll_ms=0)
        router.add_replica(s1.host, s1.port, name="prefill0")
        router.add_replica(s2.host, s2.port, name="batch0")
        router.poll_now()
        try:
            x = np.zeros((1, 4), np.float32)
            for _ in range(3):
                router.infer(x, timeout=60.0)
            reps = router.replicas()
            assert reps["prefill0"]["dispatched"] == 0
            assert reps["batch0"]["dispatched"] == 3
        finally:
            router.close(); s1.close(); s2.close(); eng.close()


class TestDrainKnob:
    def test_close_reads_decode_drain_timeout(self, params):
        mxconfig.set_override("MXNET_DECODE_DRAIN_TIMEOUT", 5.0)
        try:
            assert drain_timeout() == 5.0
            dec = ContinuousDecoder(_gen(params, B))
            dec.close()                    # knob-resolved, no raise
        finally:
            mxconfig.clear_override("MXNET_DECODE_DRAIN_TIMEOUT")

    @pytest.mark.parametrize("bad", [0.0, -3.0, float("nan"),
                                     float("inf")])
    def test_invalid_drain_timeout_is_loud(self, bad, params):
        mxconfig.set_override("MXNET_DECODE_DRAIN_TIMEOUT", bad)
        try:
            with pytest.raises(ValueError,
                               match="MXNET_DECODE_DRAIN_TIMEOUT"):
                drain_timeout()
            dec = ContinuousDecoder(_gen(params, B))
            with pytest.raises(ValueError,
                               match="MXNET_DECODE_DRAIN_TIMEOUT"):
                dec.close()
            dec.close(timeout=10.0)        # explicit budget still works
        finally:
            mxconfig.clear_override("MXNET_DECODE_DRAIN_TIMEOUT")

    def test_recycle_of_decode_replica_uses_decode_knob(self, params):
        """recycle() budgets a decode replica's drain from
        MXNET_DECODE_DRAIN_TIMEOUT (the same clock close() honors):
        with the knob invalid, recycling the decode replica trips its
        loud validation while recycling a batch replica never reads
        it."""
        from mxnet_tpu.serve import ServeEngine

        class _Echo:
            def forward(self, *arrays):
                return [np.asarray(arrays[0])]
        eng = ServeEngine(_Echo(), buckets=(1,), max_wait_ms=0.0,
                          feature_shapes=[(4,)], install_sigterm=False)
        dec_eng = ContinuousDecoder(_gen(params, B))
        s1, s2 = ServeServer(eng), ServeServer(dec_eng)
        router = ServeRouter(poll_ms=0)
        router.add_replica(s1.host, s1.port, name="batch0")
        router.add_replica(s2.host, s2.port, name="decode0")
        router.poll_now()
        mxconfig.set_override("MXNET_DECODE_DRAIN_TIMEOUT",
                              float("nan"))
        try:
            with pytest.raises(ValueError,
                               match="MXNET_DECODE_DRAIN_TIMEOUT"):
                router.recycle("decode0")
            router.recycle("batch0", warm=False)   # knob never read
        finally:
            mxconfig.clear_override("MXNET_DECODE_DRAIN_TIMEOUT")
            router.close(); s1.close(); s2.close()
            eng.close(); dec_eng.close()


class TestTraceJoin:
    def test_one_trace_spans_prefill_handoff_decode(self, params,
                                                    tmp_path):
        """The disaggregated request is ONE trace: the router generate
        span parents the prefill and decode legs, and the decode
        replica's import/seq spans join via the wire tc."""
        from mxnet_tpu import trace
        from tools.trace_report import load

        dest = tmp_path / "trace.jsonl"
        trace.start_tracing(str(dest))
        pre_eng, dec_eng, s1, s2, router = TestWire()._fleet(params)
        try:
            router.generate(np.arange(1, 6), 4, eos_id=0)
        finally:
            router.close(); s1.close(); s2.close(); dec_eng.close()
            trace.stop_tracing()
        spans = [r for r in load(str(dest))
                 if r.get("kind") == "span"]
        names = {s["name"] for s in spans}
        for want in ("serve.router.generate", "serve.router.prefill",
                     "serve.router.decode", "serve.prefill.request",
                     "serve.generate.request", "serve.prefill",
                     "serve.decode.import", "serve.decode.seq"):
            assert want in names, (want, sorted(names))
        tid = next(s["trace"] for s in spans
                   if s["name"] == "serve.router.generate")
        joined = {s["name"] for s in spans if s["trace"] == tid}
        assert {"serve.prefill", "serve.decode.import",
                "serve.decode.seq"} <= joined
