"""Native C++ RecordIO reader tests (mxnet_tpu/_native/recordio.cc) —
parity against the Python reader, including continuation-split records
(payloads embedding the aligned magic word)."""
import os
import struct

import numpy as np
import pytest

from mxnet_tpu import recordio
from mxnet_tpu.recordio import MXIndexedRecordIO, MXRecordIO

MAGIC = struct.pack("<I", 0xced7230a)


def _write_corpus(path, n=50, seed=0):
    rng = np.random.RandomState(seed)
    rec = MXRecordIO(path, "w")
    payloads = []
    for i in range(n):
        if i % 7 == 3:
            # force the continuation-split path: magic embedded at a
            # 4-byte-aligned position
            payload = b"abcd" + MAGIC + rng.bytes(8) + MAGIC + b"tail"
        else:
            payload = rng.bytes(int(rng.randint(1, 64)))
        payloads.append(payload)
        rec.write(payload)
    rec.close()
    return payloads


def _native_available():
    from mxnet_tpu._native import load
    return load("recordio") is not None


pytestmark = pytest.mark.skipif(not _native_available(),
                                reason="g++ toolchain unavailable")


def test_native_reader_matches_writes(tmp_path):
    path = str(tmp_path / "c.rec")
    payloads = _write_corpus(path)
    from mxnet_tpu._native import NativeRecordFile
    f = NativeRecordFile(path)
    assert len(f) == len(payloads)
    for i, want in enumerate(payloads):
        assert f.read(i) == want
    f.close()


def test_sequential_read_uses_native_and_matches_python(tmp_path,
                                                        monkeypatch):
    path = str(tmp_path / "c.rec")
    payloads = _write_corpus(path)

    rec = MXRecordIO(path, "r")
    assert rec._native is not None
    got_native = [rec.read() for _ in range(len(payloads))]
    assert rec.read() is None
    rec.close()

    monkeypatch.setenv("MXNET_NATIVE_RECORDIO", "0")
    rec = MXRecordIO(path, "r")
    assert rec._native is None
    got_python = [rec.read() for _ in range(len(payloads))]
    rec.close()

    assert got_native == got_python == payloads


def test_indexed_read_via_native(tmp_path):
    rec_path = str(tmp_path / "i.rec")
    idx_path = str(tmp_path / "i.idx")
    w = MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(1)
    payloads = {}
    for key in range(30):
        payload = rng.bytes(int(rng.randint(1, 40))) \
            if key % 5 else b"xx" + MAGIC + MAGIC + b"yy"
        payloads[key] = payload
        w.write_idx(key, payload)
    w.close()

    r = MXIndexedRecordIO(idx_path, rec_path, "r")
    assert r._native is not None
    for key in (0, 29, 5, 17, 5, 0):
        assert r.read_idx(key) == payloads[key]
    r.close()


def test_reset_restarts_native_cursor(tmp_path):
    path = str(tmp_path / "r.rec")
    payloads = _write_corpus(path, n=5)
    rec = MXRecordIO(path, "r")
    assert rec.read() == payloads[0]
    rec.reset()
    assert rec.read() == payloads[0]
    rec.close()


def test_seek_then_read(tmp_path):
    """Public seek()+read() pattern must honour the seek position."""
    rec_path = str(tmp_path / "s.rec")
    idx_path = str(tmp_path / "s.idx")
    w = MXIndexedRecordIO(idx_path, rec_path, "w")
    for key in range(10):
        w.write_idx(key, b"rec%03d" % key)
    w.close()
    r = MXIndexedRecordIO(idx_path, rec_path, "r")
    assert r._native is not None
    r.seek(7)
    assert r.read() == b"rec007"
    assert r.read() == b"rec008"   # cursor advanced past the seek point
    r.close()


def test_corrupt_file_falls_back_to_strict_reader(tmp_path):
    path = str(tmp_path / "bad.rec")
    payloads = _write_corpus(path, n=3)
    blob = bytearray(open(path, "rb").read())
    blob.extend(b"\x01\x02\x03\x04garbage!")     # torn tail
    open(path, "wb").write(bytes(blob))
    r = MXRecordIO(path, "r")
    assert r._native is None      # native scanner refused the file
    for want in payloads:
        assert r.read() == want
    with pytest.raises(AssertionError):
        r.read()                  # strict reader raises at the tear
    r.close()


def test_tell_tracks_records_in_native_mode(tmp_path):
    """The classic index-building loop: pos = tell(); read()."""
    path = str(tmp_path / "t.rec")
    payloads = _write_corpus(path, n=6)
    import os as _os
    _os.environ.pop("MXNET_NATIVE_RECORDIO", None)
    r = MXRecordIO(path, "r")
    assert r._native is not None
    positions = []
    while True:
        pos = r.tell()
        if r.read() is None:
            break
        positions.append(pos)
    r.close()

    # positions must let a strict python reader seek+read each record
    r = MXRecordIO(path, "r")
    r._native.close()
    r._native = None
    for pos, want in zip(positions, payloads):
        r.fp.seek(pos)
        assert r.read() == want
    r.close()


def test_pack_unpack_roundtrip_through_native(tmp_path):
    path = str(tmp_path / "p.rec")
    rec = MXRecordIO(path, "w")
    header = recordio.IRHeader(0, 3.5, 7, 0)
    rec.write(recordio.pack(header, b"payload"))
    rec.close()
    rec = MXRecordIO(path, "r")
    got_header, blob = recordio.unpack(rec.read())
    assert got_header.label == 3.5 and blob == b"payload"
    rec.close()
